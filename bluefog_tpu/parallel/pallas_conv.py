"""Pallas fused 1x1-conv backward — the ResNet bandwidth kernel.

Round-2 verdict item 1 ("hand-scheduled conv-backward kernel").  The
whole-step audit (benchmarks/profile_resnet_convs.py + XLA cost
analysis) shows batch-128 ResNet-50 on v5e is **HBM-bandwidth-bound**:
the forward runs at the bandwidth roofline and the backward's wall is
the 1x1 convolutions — pure matmuls whose XLA backward materializes
transposed operands and reads the upstream cotangent twice (once for
the input gradient, once for the weight gradient).

This kernel computes BOTH gradients in ONE pass over the data:

    dx[n, ci] = dy[n, co] @ w[ci, co]^T        (MXU, per tile)
    dw[ci, co] += x[n, ci]^T @ dy[n, co]       (MXU, accumulated in VMEM)

Each N-tile of ``x`` and ``dy`` is loaded from HBM exactly once; ``dw``
lives in a float32 VMEM accumulator across the whole grid (constant
output index map) and is written back once.  Ideal traffic is
``|x| + |dy| + |dx| + |dw|`` — the information-theoretic floor.
The transposed contractions are expressed as ``dot_general`` dimension
numbers, so no transposed copy of any N-sized tensor is ever
materialized.

The forward path stays with XLA (a 1x1 conv IS a matmul and already
runs at the roofline); only the backward is hand-scheduled, wired in
through ``jax.custom_vjp``.  Strided 1x1 convs (ResNet's projection
shortcuts) are handled by slicing the input at stride positions in the
forward and scattering ``dx`` back through the same positions — the
kernel itself always sees the dense stride-1 problem.

Reference counterpart: the CUDA ScaleBuffer kernel era of hand-written
device code (reference bluefog/cuda/cuda_kernels.cu) — here the hot op
is the conv backward, not the weighted combine (which XLA already
fuses, docs/performance.md).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["conv1x1", "conv1x1_backward"]


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _tile_n(n: int, ci: int, co: int) -> int:
    """Largest divisor of n fitting the ~16 MB scoped-VMEM budget:
    resident blocks (w bf16 + dw f32 output + f32 accumulator scratch =
    10*ci*co bytes) plus DOUBLE-buffered streaming x/dy/dx blocks.
    Prefers sublane-aligned (multiple-of-8) divisors."""
    # Mosaic pads the lane (last) dim to 128: budget with PADDED widths.
    # Resident: w^T [co, ci] (bf16) + dw out [ci, co] (f32) + acc
    # scratch [ci, co] (f32); streaming: x/dx [tn, ci] + dy [tn, co],
    # double-buffered.
    ci_p = -(-ci // 128) * 128
    co_p = -(-co // 128) * 128
    budget = 11 * 1024 * 1024 - (2 * co * ci_p + 8 * ci * co_p)
    row_bytes = 2 * 2 * (2 * ci_p + co_p)  # bf16 x + dx + dy, dbl-buffered
    target = max(min(budget // max(row_bytes, 1), n), 1)
    best = 1
    for t in range(min(target, n), 0, -1):
        if n % t == 0:
            if t % 8 == 0:
                return t  # first (largest) aligned divisor wins
            best = max(best, t)
    return best


def _bwd_kernel(x_ref, dy_ref, wt_ref, dx_ref, dw_ref, acc_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    dy = dy_ref[:]
    # dx = dy @ w^T (w passed pre-transposed [co, ci]: the canonical
    # contract-dim1-with-dim0 MXU matmul) -> [TN, ci]
    dx = lax.dot_general(dy, wt_ref[:],
                         dimension_numbers=(((1,), (0,)), ((), ())),
                         preferred_element_type=jnp.float32)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    # dw += x^T @ dy: contract N (dim 0 of both) -> [ci, co], f32 VMEM
    # scratch accumulator (NOT an output-block revisit, which would
    # serialize the dx output pipeline)
    acc_ref[:] += lax.dot_general(x_ref[:], dy,
                                  dimension_numbers=(((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        dw_ref[:] = acc_ref[:]


def conv1x1_backward(x2d: jax.Array, dy2d: jax.Array, w: jax.Array,
                     interpret: Optional[bool] = None):
    """Fused (dx, dw) for ``y = x2d @ w``.

    x2d [N, ci], dy2d [N, co], w [ci, co]; returns dx2d [N, ci] in
    x2d's dtype and dw [ci, co] in float32 (accumulated in f32 on the
    MXU regardless of input dtype).
    """
    n, ci = x2d.shape
    co = dy2d.shape[1]
    tn = _tile_n(n, ci, co)
    if tn < 64:
        # Resident w/dw/accumulator blocks leave no VMEM for streaming
        # (huge ci*co, e.g. the 1024->2048 projection): XLA's backward
        # is the better program there
        dx = lax.dot_general(dy2d, w,
                             dimension_numbers=(((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        dw = lax.dot_general(x2d, dy2d,
                             dimension_numbers=(((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
        return dx.astype(x2d.dtype), dw
    grid = (n // tn,)
    dx, dw = pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tn, ci), lambda i: (i, 0)),
            pl.BlockSpec((tn, co), lambda i: (i, 0)),
            pl.BlockSpec((co, ci), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tn, ci), lambda i: (i, 0)),
            pl.BlockSpec((ci, co), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, ci), x2d.dtype),
            jax.ShapeDtypeStruct((ci, co), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((ci, co), jnp.float32)],
        interpret=_auto_interpret(interpret),
    )(x2d, dy2d, w.T)
    return dx, dw


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def conv1x1(x: jax.Array, w: jax.Array, stride: int = 1,
            interpret: Optional[bool] = None) -> jax.Array:
    """1x1 convolution ``y[b,i,j,co] = sum_ci x[b,si,sj,ci] w[ci,co]``
    with the Pallas fused backward.

    ``x`` NHWC, ``w`` [ci, co] (squeeze the [1,1,ci,co] conv kernel).
    Forward is a plain XLA matmul (already bandwidth-optimal); backward
    is one fused Pallas pass producing dx and dw together.
    """
    return _fwd_impl(x, w, stride)


def _fwd_impl(x, w, stride):
    if stride > 1:
        x = x[:, ::stride, ::stride, :]
    b, h, wd, ci = x.shape
    y = lax.dot_general(x.reshape(-1, ci), w,
                        dimension_numbers=(((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
    return y.reshape(b, h, wd, -1).astype(x.dtype)


def _conv1x1_fwd(x, w, stride, interpret):
    return _fwd_impl(x, w, stride), (x, w)


def _conv1x1_bwd(stride, interpret, res, dy):
    x, w = res
    xs = x[:, ::stride, ::stride, :] if stride > 1 else x
    b, h, wd, ci = xs.shape
    dy2d = dy.reshape(-1, dy.shape[-1]).astype(xs.dtype)
    dx2d, dw = conv1x1_backward(xs.reshape(-1, ci), dy2d,
                                w.astype(xs.dtype), interpret=interpret)
    dxs = dx2d.reshape(b, h, wd, ci)
    if stride > 1:
        dx = jnp.zeros(x.shape, dxs.dtype).at[:, ::stride, ::stride, :].set(
            dxs)
    else:
        dx = dxs
    return dx, dw.astype(w.dtype)


conv1x1.defvjp(_conv1x1_fwd, _conv1x1_bwd)
