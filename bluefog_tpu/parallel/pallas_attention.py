"""Pallas TPU flash-attention kernel.

The reference's only custom kernel is a CUDA buffer-scale
(reference bluefog/common/cuda/cuda_kernels.cu); SURVEY.md §7.9 calls for
Pallas kernels where the TPU build needs custom compute.  Attention is the
hot op of the Llama stress config, so this is the first one: a blockwise
online-softmax (flash) kernel that keeps the score matrix in VMEM, streams
K/V blocks, and optionally returns the log-sum-exp residual so callers can
merge partial attentions — exactly what ring attention needs per ring step.

Design:
* grid = (batch*heads, query blocks); per instance the q block lives in
  VMEM, K/V stream as [T_k, D] slices; scores/accumulator in f32.
* GQA without widening: the K/V BlockSpec index map folds query head h to
  kv head h // (H/H_kv) — no repeated K/V in HBM or VMEM.
* global position offsets arrive as SMEM scalars, so the same compiled
  kernel serves every ring step (offsets are traced values).
* backward = two blockwise Pallas passes (dQ over K blocks; dK/dV over Q
  blocks) using the saved (out, lse) residuals and the standard
  delta = rowsum(dO * O) trick — no T x T matrix ever materializes, so
  long-context training stays VMEM/HBM bounded by single tiles.

Interpret mode (CPU tests) is selected automatically off the backend.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_with_lse"]

_NEG_INF = -1e30


def _apply_causal_mask(s, q_off, kv_off, qi, kj):
    """Mask scores s [block_q, block_k] with the GLOBAL causal rule
    q_pos >= kv_pos, where positions include the ring-step offsets held in
    SMEM.  Single source of truth for forward, dQ and dK/dV kernels."""
    block_q, block_k = s.shape
    q_pos = (q_off + qi * block_q +
             jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0))
    kv_pos = (kv_off + kj * block_k +
              jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1))
    return jnp.where(q_pos >= kv_pos, s, _NEG_INF)


def _kv_index_map(h: int, h_kv: int):
    """BlockSpec index map folding query-head grid rows onto KV heads:
    row bh = batch*H + head  ->  kv row batch*H_kv + head // (H/H_kv)."""
    group = h // h_kv

    def kv_index(bh, qi, kj):
        return (bh // h * h_kv + (bh % h) // group, kj, 0)

    return kv_index


def _block_live(q_off, kv_off, qi, kj, block_q, block_k):
    """False iff the (qi, kj) score block is ENTIRELY above the causal
    diagonal (every kv_pos > every q_pos) — its probabilities are all
    zero, so the dots and softmax update can be skipped outright.  The
    skipped fraction is (n_k - 1)/(2 n_k) of the grid: 25% at seq 2048
    with 1024-wide k blocks, approaching half as sequences grow (the
    round-5 roofline measured the unskipped kernel at 9-10% MFU while
    every matmul sat at 94-97% — attention IS the MFU wall, and the
    above-diagonal blocks were pure masked work)."""
    q_max = q_off + qi * block_q + block_q - 1
    kv_min = kv_off + kj * block_k
    return kv_min <= q_max


def _static_offs(q_offset, kv_offset):
    """(q_offset, kv_offset) when both are compile-time ints (the
    full-sequence path), else None (ring steps trace them) — the ONE
    place the staticness rule lives."""
    if isinstance(q_offset, int) and isinstance(kv_offset, int):
        return (q_offset, kv_offset)
    return None


def _clamp_dead_kv(kv_index, q_offset, kv_offset, block_q, block_k,
                   causal: bool):
    """Wrap a K/V BlockSpec index map so DEAD (qi, kj) blocks re-request
    the row's LAST LIVE kj — Pallas elides the HBM->VMEM copy when the
    block index repeats, so skipped blocks stop paying their DMA too.
    Only possible when the ring offsets are STATIC python ints (the
    full-sequence training path; ring attention's traced offsets keep
    the plain map — its blocks are live or about to rotate anyway)."""
    if not causal or _static_offs(q_offset, kv_offset) is None:
        return kv_index

    def clamped(bh, qi, kj):
        last_live = (q_offset + (qi + 1) * block_q - 1
                     - kv_offset) // block_k
        kj_eff = jnp.minimum(kj, jnp.maximum(last_live, 0))
        return kv_index(bh, qi, kj_eff)

    return clamped


def _kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
            m_ref, l_ref, acc_ref, *, causal: bool, scale: float,
            offs=None):
    """Grid = (batch*heads, q blocks, k blocks).  Only one (block_q, D) Q
    tile and one (block_k, D) K/V tile are resident in VMEM per instance —
    long sequences never stage whole K/V on chip.  The online-softmax state
    (m, l, acc) lives in VMEM scratch, which persists across the innermost
    (k-block) grid dimension.  Causal mode skips fully-masked k blocks
    (``_block_live``)."""
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    qi = pl.program_id(1)
    # STATIC ring offsets (the full-sequence path) fold the mask/skip
    # arithmetic into compile-time constants — no SMEM scalar reads in
    # the hot loop; traced offsets (ring steps) read the SMEM refs.
    q_off = offs[0] if offs is not None else q_off_ref[0]
    kv_off = offs[1] if offs is not None else kv_off_ref[0]
    # NATIVE-dtype dot operands with f32 accumulation: numerically
    # IDENTICAL for the score matmul (the MXU multiplies the same bf16
    # mantissas either way); the P·V dot rounds the f32 probabilities
    # to the value dtype (f32 inputs stay exact; bf16 inputs get the
    # standard FlashAttention mixed-precision PV dot).  Measured
    # end-to-end NEUTRAL (docs/performance.md round 5: Mosaic already
    # absorbed the old operand upcasts) — kept as the cleaner form, not
    # as a perf lever; the kernel's cost sits in the softmax's
    # cross-lane reductions, also measured there.
    q = q_ref[0]                          # [block_q, D]
    block_q, d = q.shape
    block_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _():
        # m/l live as [block_q, 128] LANE-REPLICATED tiles, not 1-D
        # vectors: the row-reduction results (max/sum with keepdims)
        # stay in the score tile's sublane layout and broadcasts read a
        # full lane tile (1-D stats measured ~1.4x slower fwd than the
        # jax reference kernel, which replicates its stats the same
        # way; [bq, 1] columns recovered most of it, [bq, 128] the
        # rest — 4.02 -> 3.19 -> 2.86 ms at the 1B shapes)
        m_ref[:] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[:] = jnp.zeros(l_ref.shape, jnp.float32)
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    live = _block_live(q_off, kv_off, qi, kj,
                       block_q, block_k) if causal else True

    @pl.when(live)
    def _():
        k_blk = k_ref[0]                  # [block_k, D]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk] f32
        if causal:
            s = _apply_causal_mask(s, q_off, kv_off, qi, kj)
        m, l = m_ref[:, :1], l_ref[:, :1]               # [bq, 1] views
        acc = acc_ref[:]
        blk_m = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, blk_m)
        p = jnp.exp(s - new_m)
        if causal:
            # fully-masked rows have s == new_m == _NEG_INF, where the
            # subtraction would give exp(0) = 1; zero them explicitly
            p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - new_m)
        lanes = m_ref.shape[1]
        m_ref[:] = jnp.broadcast_to(new_m, (block_q, lanes))
        l_ref[:] = jnp.broadcast_to(
            l * corr + jnp.sum(p, axis=-1, keepdims=True),
            (block_q, lanes))
        acc_ref[:] = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == n_k - 1)
    def _():
        l_final = l_ref[:, :1]
        safe_l = jnp.maximum(l_final, 1e-30)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # lse = m + log(l); fully-masked rows stay at ~_NEG_INF
        lse_ref[0] = jnp.where(l_final > 0,
                               m_ref[:, :1] + jnp.log(safe_l), _NEG_INF)


def _fit_block(t: int, want: int) -> int:
    """Largest divisor of ``t`` that is <= ``want`` — block sizes must tile
    the sequence exactly (no tail handling in the kernel)."""
    want = min(want, t)
    for b in range(want, 0, -1):
        if t % b == 0:
            return b
    return 1


def _flash_fwd_impl(q, k, v, q_offset, kv_offset, *, causal, scale,
                    block_q, block_k, interpret):
    b, t_q, h, d = q.shape
    h_kv = k.shape[2]
    t_k = k.shape[1]
    block_q = _fit_block(t_q, block_q)
    block_k = _fit_block(t_k, block_k)

    # [B, T, H, D] -> [B*H, T, D] (kv keeps its narrow head count)
    qt = jnp.moveaxis(q, 2, 1).reshape(b * h, t_q, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * h_kv, t_k, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * h_kv, t_k, d)
    q_off = jnp.reshape(jnp.asarray(q_offset, jnp.int32), (1,))
    kv_off = jnp.reshape(jnp.asarray(kv_offset, jnp.int32), (1,))

    kv_index = _clamp_dead_kv(_kv_index_map(h, h_kv), q_offset, kv_offset,
                              block_q, block_k, causal)
    offs = _static_offs(q_offset, kv_offset)
    grid = (b * h, t_q // block_q, t_k // block_k)
    out, lse = pl.pallas_call(
        functools.partial(_kernel, causal=causal, scale=scale, offs=offs),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0)),
            # trailing singleton keeps the block TPU-tileable (last dim
            # equals the array dim; second-to-last is the 8-aligned block_q)
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, t_q, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max m
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom l
            pltpu.VMEM((block_q, d), jnp.float32),    # running numer acc
        ],
        interpret=interpret,
    )(q_off, kv_off, qt, kt, vt)
    out = jnp.moveaxis(out.reshape(b, h, t_q, d), 1, 2)
    lse = lse.reshape(b, h, t_q)
    return out, lse


def _recompute_p(q, k, lse, q_off, kv_off, qi, kj, scale, causal):
    """Recompute the normalized probability block P = exp(S - lse) with the
    global causal mask; fully-masked entries (S == _NEG_INF) go to 0 even
    when the whole row is masked (lse == _NEG_INF would give exp(0)).
    ``lse`` is a [block_q, 1] column (sublane-aligned with the score
    tile — see the forward kernel's scratch note)."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        s = _apply_causal_mask(s, q_off, kv_off, qi, kj)
    p = jnp.exp(s - lse)
    return jnp.where(s <= _NEG_INF / 2, 0.0, p)


def _bwd_dq_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                   lse_ref, delta_ref, dq_ref, acc_ref, *, causal, scale,
                   offs=None):
    """Grid (bh, qi, kj): accumulate dQ_i = sum_j dS_ij K_j * scale."""
    kj = pl.program_id(2)
    n_k = pl.num_programs(2)
    qi = pl.program_id(1)
    q_off = offs[0] if offs is not None else q_off_ref[0]
    kv_off = offs[1] if offs is not None else kv_off_ref[0]
    # native-dtype dot operands, f32 accumulation (see _kernel's note)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]          # [block_q, 1] columns, sublane-aligned
    delta = delta_ref[0]
    block_q, d = q.shape
    block_k = k_ref.shape[1]

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

    live = _block_live(q_off, kv_off, qi, kj,
                       block_q, block_k) if causal else True

    @pl.when(live)
    def _():
        k = k_ref[0]
        v = v_ref[0]
        p = _recompute_p(q, k, lse, q_off, kv_off, qi, kj,
                         scale, causal)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(kj == n_k - 1)
    def _():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_off_ref, kv_off_ref, q_ref, k_ref, v_ref, do_ref,
                    lse_ref, delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    causal, scale, group, offs=None):
    """Grid (b*h_kv, kj, qi*group): accumulate dK_j / dV_j over every query
    block and every query head in this KV head's group."""
    t = pl.program_id(2)
    n_t = pl.num_programs(2)
    qi = t // group
    q_off = offs[0] if offs is not None else q_off_ref[0]
    kv_off = offs[1] if offs is not None else kv_off_ref[0]
    # native-dtype dot operands, f32 accumulation (see _kernel's note)
    q = q_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]          # [block_q, 1] columns, sublane-aligned
    delta = delta_ref[0]
    block_q, d = q.shape
    block_k = k_ref.shape[1]

    @pl.when(t == 0)
    def _():
        dk_acc[:] = jnp.zeros((block_k, d), jnp.float32)
        dv_acc[:] = jnp.zeros((block_k, d), jnp.float32)

    kj = pl.program_id(1)
    live = _block_live(q_off, kv_off, qi, kj,
                       block_q, block_k) if causal else True

    @pl.when(live)
    def _():
        k = k_ref[0]
        v = v_ref[0]
        p = _recompute_p(q, k, lse, q_off, kv_off, qi, kj,
                         scale, causal)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(t == n_t - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, out, lse, do, q_offset, kv_offset, *, causal,
                    scale, block_q, block_k, interpret):
    b, t_q, h, d = q.shape
    h_kv, t_k = k.shape[2], k.shape[1]
    group = h // h_kv
    block_q = _fit_block(t_q, block_q)
    block_k = _fit_block(t_k, block_k)
    # Both bwd kernels materialize TWO f32 [block_q, block_k] score-sized
    # intermediates (p and dp) — cap their product at 1M elements (8 MB)
    # so large k tiles (which the forward can afford with its single
    # score buffer) don't blow the 16 MB scoped-VMEM budget here; the
    # q tile shrinks instead, which bwd tolerates (its accumulators are
    # keyed on k blocks).
    while block_q * block_k > (1 << 20) and block_q > 8:
        block_q = _fit_block(t_q, block_q // 2)

    qt = jnp.moveaxis(q, 2, 1).reshape(b * h, t_q, d)
    kt = jnp.moveaxis(k, 2, 1).reshape(b * h_kv, t_k, d)
    vt = jnp.moveaxis(v, 2, 1).reshape(b * h_kv, t_k, d)
    dot = jnp.moveaxis(do, 2, 1).reshape(b * h, t_q, d)
    lse3 = lse.reshape(b * h, t_q, 1)
    # delta = rowsum(dO * O), the softmax-jacobian diagonal term
    delta3 = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                     axis=-1)  # [B, T, H]
    delta3 = jnp.moveaxis(delta3, 2, 1).reshape(b * h, t_q, 1)
    q_off = jnp.reshape(jnp.asarray(q_offset, jnp.int32), (1,))
    kv_off = jnp.reshape(jnp.asarray(kv_offset, jnp.int32), (1,))

    kv_index = _clamp_dead_kv(_kv_index_map(h, h_kv), q_offset, kv_offset,
                              block_q, block_k, causal)
    offs = _static_offs(q_offset, kv_offset)
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale,
                          offs=offs),
        grid=(b * h, t_q // block_q, t_k // block_k),
        in_specs=[smem, smem, q_spec,
                  pl.BlockSpec((1, block_k, d), kv_index),
                  pl.BlockSpec((1, block_k, d), kv_index),
                  q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, t_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q_off, kv_off, qt, kt, vt, dot, lse3, delta3)

    # dK/dV: grid row is a KV head; the innermost dim sweeps (q block,
    # group member) pairs so GQA head sums accumulate in scratch instead of
    # materializing widened dK/dV.
    def q_row(bkv, kj, t):
        qi = t // group
        if causal and offs is not None:
            # dead (low-qi) steps re-request the kj row's FIRST LIVE q
            # block so their elided DMAs match the skipped compute
            # (same trick as _clamp_dead_kv; with equal static spans the
            # first live qi always exists)
            first_live = (kv_offset + kj * block_k - q_offset
                          + block_q - 1) // block_q
            qi = jnp.maximum(qi, first_live)
        return ((bkv // h_kv) * h + (bkv % h_kv) * group + t % group,
                qi, 0)

    kv_self = pl.BlockSpec((1, block_k, d), lambda bkv, kj, t: (bkv, kj, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale,
                          group=group, offs=offs),
        grid=(b * h_kv, t_k // block_k, (t_q // block_q) * group),
        in_specs=[smem, smem,
                  pl.BlockSpec((1, block_q, d), q_row),
                  kv_self, kv_self,
                  pl.BlockSpec((1, block_q, d), q_row),
                  pl.BlockSpec((1, block_q, 1), q_row),
                  pl.BlockSpec((1, block_q, 1), q_row)],
        out_specs=[kv_self, kv_self],
        out_shape=[jax.ShapeDtypeStruct((b * h_kv, t_k, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h_kv, t_k, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(q_off, kv_off, qt, kt, vt, dot, lse3, delta3)

    dq = jnp.moveaxis(dq.reshape(b, h, t_q, d), 1, 2)
    dk = jnp.moveaxis(dk.reshape(b, h_kv, t_k, d), 1, 2)
    dv = jnp.moveaxis(dv.reshape(b, h_kv, t_k, d), 1, 2)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, q_offset, kv_offset, causal, scale, block_q, block_k,
           interpret):
    out, _ = _flash_fwd_impl(q, k, v, q_offset, kv_offset, causal=causal,
                             scale=scale, block_q=block_q, block_k=block_k,
                             interpret=interpret)
    return out


def _flash_fwd(q, k, v, q_offset, kv_offset, causal, scale, block_q, block_k,
               interpret):
    out, lse = _flash_fwd_impl(q, k, v, q_offset, kv_offset, causal=causal,
                               scale=scale, block_q=block_q, block_k=block_k,
                               interpret=interpret)
    return out, (q, k, v, out, lse, q_offset, kv_offset)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, q_offset, kv_offset = res
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, out, lse, g, q_offset, kv_offset, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def _auto_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    # 1024 tiles measured +18%/+13% end-to-end on v5e at head_dim 64
    # (round 3, docs/performance.md); _fit_block clamps to t's divisors
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention.  q: [B, T_q, H, D]; k/v: [B, T_k, H_kv, D] (GQA
    served by index mapping, never materialized).  Differentiable
    (recompute-based backward).  Mixed-dtype q/k/v are normalized to
    q's dtype (the kernels feed operands to the MXU natively)."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    return _flash(q, k, v, q_offset, kv_offset, causal, scale, block_q,
                  block_k, _auto_interpret(interpret))


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: Optional[float] = None,
    q_offset=0,
    kv_offset=0,
    # 1024 tiles measured +18%/+13% end-to-end on v5e at head_dim 64
    # (round 3, docs/performance.md); _fit_block clamps to t's divisors
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Forward-only variant returning (out, lse) with
    lse[b, h, t] = logsumexp of that row's masked scores — the residual
    needed to merge partial attentions across ring steps."""
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    k = k.astype(q.dtype)
    v = v.astype(q.dtype)
    return _flash_fwd_impl(q, k, v, q_offset, kv_offset, causal=causal,
                           scale=scale, block_q=block_q, block_k=block_k,
                           interpret=_auto_interpret(interpret))
