"""Input pipeline: sharded, shuffled, prefetching data loading.

The reference has no input pipeline of its own — every example iterates a
``torch.utils.data.DataLoader`` with a ``DistributedSampler`` partitioning
the dataset by rank (reference examples/pytorch_mnist.py,
pytorch_resnet.py).  A standalone framework needs its own; this one is
TPU-shaped:

* **Sampling lives in Python** (``DistributedSampler``): per-epoch global
  permutation -> per-rank disjoint shards, torch-DistributedSampler
  semantics (pad-by-wrapping unless ``drop_last``).  Keeping index math in
  one place makes the native and pure-Python paths bit-identical.
* **Gathering lives in C++** (``native.NativeBatchPipeline``): worker
  threads copy scattered records into a ring of pre-allocated contiguous
  batch buffers, overlapping host-side batch assembly with device compute.
  Falls back to a Python thread when the native library is unavailable.
* **Rank-major delivery**: under single-process SPMD (the normal BlueFog-
  TPU shape) ``DataLoader(..., rank_major=True)`` yields global
  ``[world, per_rank_batch, ...]`` arrays ready for ``device_put`` with a
  rank-major sharding — each rank's row is its own disjoint shard stream.
* ``device_prefetch`` overlaps host->device transfer one batch ahead.
"""

from __future__ import annotations

import gzip
import os
import pickle
import queue
import struct
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.logging_util import get_logger

__all__ = ["DistributedSampler", "DataLoader", "device_prefetch",
           "load_mnist", "load_cifar10"]

logger = get_logger()


class DistributedSampler:
    """Per-epoch index streams: one global permutation, sharded by rank.

    Semantics follow torch's DistributedSampler (the sampler the reference's
    examples use): when ``drop_last`` is False the index list is padded by
    wrapping so every rank gets the same count; when True the tail that
    doesn't divide evenly is dropped.  ``set_epoch`` (or the ``epoch``
    argument) reshuffles deterministically from ``seed``.
    """

    def __init__(self, n_items: int, rank: int = 0, world: int = 1,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside world {world}")
        self.n_items = int(n_items)
        self.rank = rank
        self.world = world
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        if drop_last:
            self.num_samples = self.n_items // world
        else:
            self.num_samples = -(-self.n_items // world)  # ceil
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def indices(self, epoch: Optional[int] = None) -> np.ndarray:
        """This rank's sample indices for ``epoch`` (local view of the
        shared global permutation)."""
        if epoch is None:
            epoch = self.epoch
        if self.shuffle:
            rng = np.random.Generator(
                np.random.Philox(key=self.seed + epoch))
            order = rng.permutation(self.n_items)
        else:
            order = np.arange(self.n_items)
        total = self.num_samples * self.world
        if total > len(order):  # pad by wrapping/tiling (not drop_last)
            reps = -(-total // len(order))
            order = np.tile(order, reps)
        order = order[:total]
        # interleaved assignment (rank r takes order[r::world]), matching
        # torch's DistributedSampler
        return np.ascontiguousarray(order[self.rank::self.world])

    def __iter__(self):
        return iter(self.indices())

    def __len__(self):
        return self.num_samples


def _read_idx(path: str) -> np.ndarray:
    """Read one IDX-format file (the MNIST wire format), gzipped or raw.

    IDX header: 2 zero bytes, a type code (0x08 = uint8), the number of
    dimensions, then that many big-endian uint32 dim sizes, then the raw
    data.  Only uint8 payloads are supported (all of MNIST is)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as fh:
        zero, dtype_code, ndim = struct.unpack(">HBB", fh.read(4))
        if zero != 0 or dtype_code != 0x08:
            raise ValueError(
                f"{path}: not a uint8 IDX file "
                f"(header {zero:#06x} {dtype_code:#04x})")
        dims = struct.unpack(">" + "I" * ndim, fh.read(4 * ndim))
        data = np.frombuffer(fh.read(), dtype=np.uint8)
    if data.size != int(np.prod(dims)):
        raise ValueError(
            f"{path}: payload {data.size} != header dims {dims}")
    return data.reshape(dims)


def _find_file(roots: Sequence[str], names: Sequence[str]) -> str:
    for root in roots:
        for name in names:
            p = os.path.join(root, name)
            if os.path.exists(p):
                return p
    raise FileNotFoundError(
        f"none of {list(names)} under {list(roots)}")


def load_mnist(root: str, split: str = "train"
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Load an on-disk MNIST in the standard IDX layout (the format the
    reference's examples consume via torchvision,
    reference examples/pytorch_mnist.py:37-49 — zero egress: this only
    READS a directory that already exists).

    Accepts ``root`` pointing at the files directly or at a torchvision-
    style tree (``root/MNIST/raw``); files may be gzipped
    (``train-images-idx3-ubyte.gz``) or raw.

    Returns ``(images [N, 28, 28, 1] float32 in [0, 1], labels [N]
    int32)`` — the shapes the shipped MLP/examples already train on.
    """
    if split not in ("train", "test"):
        raise ValueError(f"split must be 'train' or 'test', got {split!r}")
    prefix = "train" if split == "train" else "t10k"
    roots = [root, os.path.join(root, "MNIST", "raw"),
             os.path.join(root, "raw")]
    images = _read_idx(_find_file(roots, [
        f"{prefix}-images-idx3-ubyte.gz", f"{prefix}-images-idx3-ubyte",
        f"{prefix}-images.idx3-ubyte"]))
    labels = _read_idx(_find_file(roots, [
        f"{prefix}-labels-idx1-ubyte.gz", f"{prefix}-labels-idx1-ubyte",
        f"{prefix}-labels.idx1-ubyte"]))
    if images.ndim != 3 or labels.ndim != 1 \
            or images.shape[0] != labels.shape[0]:
        raise ValueError(
            f"MNIST shape mismatch: {images.shape} vs {labels.shape}")
    return (images.astype(np.float32)[..., None] / 255.0,
            labels.astype(np.int32))


def load_cifar10(root: str, split: str = "train"
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Load an on-disk CIFAR-10 in the standard python-pickle layout
    (``cifar-10-batches-py``: ``data_batch_1..5`` + ``test_batch``, each
    a pickle with ``data [10000, 3072]`` uint8 channel-major rows and
    ``labels``).  ``root`` may point at the batch directory or its
    parent.

    Returns ``(images [N, 32, 32, 3] float32 in [0, 1], labels [N]
    int32)``.
    """
    if split not in ("train", "test"):
        raise ValueError(f"split must be 'train' or 'test', got {split!r}")
    roots = [root, os.path.join(root, "cifar-10-batches-py")]
    names = ([f"data_batch_{i}" for i in range(1, 6)]
             if split == "train" else ["test_batch"])
    imgs, labels = [], []
    for name in names:
        with open(_find_file(roots, [name]), "rb") as fh:
            d = pickle.load(fh, encoding="bytes")
        data = np.asarray(d[b"data"], dtype=np.uint8)
        if data.ndim != 2 or data.shape[1] != 3072:
            raise ValueError(
                f"{name}: expected [N, 3072] uint8, got {data.shape}")
        imgs.append(data.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        labels.append(np.asarray(d[b"labels"], dtype=np.int32))
    return (np.concatenate(imgs).astype(np.float32) / 255.0,
            np.concatenate(labels))


class _PythonPipeline:
    """Fallback gather engine: one producer thread, same batch semantics
    and bit-identical output to the native pipeline."""

    _join_timeout = 10.0  # seconds a shutdown waits for the producer

    def __init__(self, fields: List[np.ndarray], batch_size: int,
                 depth: int = 3, workers: int = 1):
        del workers
        self._fields = fields
        self._batch = batch_size
        self._depth = depth
        self._q: "queue.Queue" = queue.Queue(maxsize=depth)
        self._thread: Optional[threading.Thread] = None
        self._cancel = threading.Event()
        self._closed = False

    def start_epoch(self, order) -> int:
        self._drain()
        self._closed = False  # reuse after close(): re-arm the latch so
        # the NEXT close still drains the fresh producer
        order = np.ascontiguousarray(order, dtype=np.int64)
        n_batches = -(-len(order) // self._batch)
        self._cancel = threading.Event()
        cancel = self._cancel

        def produce():
            for b in range(n_batches):
                if cancel.is_set():
                    return
                idx = order[b * self._batch:(b + 1) * self._batch]
                self._q.put([np.ascontiguousarray(f[idx])
                             for f in self._fields])
            self._q.put(None)

        self._thread = threading.Thread(target=produce, daemon=True,
                                        name="bf-data-producer")
        self._thread.start()
        return n_batches

    def next(self):
        views = self._q.get()
        if views is None:
            return None
        return 0, views

    def release(self, slot: int):
        del slot

    def _drain(self):
        thread = self._thread
        if thread is not None and thread.is_alive():
            self._cancel.set()
            while True:  # unblock a producer stuck on a full queue
                try:
                    self._q.get_nowait()
                except queue.Empty:
                    break
            thread.join(timeout=self._join_timeout)
            if thread.is_alive():
                # A producer that survives cancel + queue drain + join is
                # wedged in user code (e.g. a transform touching a dead
                # filesystem).  It is a daemon, so it cannot block process
                # exit — but it IS a leak, and silently ignoring it hides
                # the resource bug.  Name it so the log points at the
                # culprit.
                logger.warning(
                    "data prefetch shutdown: producer thread '%s' is "
                    "still alive after %.0f s (cancel + queue drain + "
                    "join); leaking it as a daemon. The producer is "
                    "stuck outside the queue protocol — check the "
                    "fields/transform it reads.",
                    thread.name, self._join_timeout)
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def close(self):
        """Shut the producer down.  Idempotent: a second close() (e.g.
        explicit close followed by __del__) is a no-op — in particular it
        does not re-log the leak warning."""
        if self._closed:
            return
        self._closed = True
        self._drain()


class DataLoader:
    """Sharded, shuffled, prefetching batch iterator over array fields.

    ``fields`` is a tuple/list of numpy arrays with a shared leading sample
    dimension (e.g. ``(images, labels)``).  Each epoch yields tuples of
    numpy batches; re-iterating reshuffles (sampler epoch auto-increments).

    With ``rank_major=True`` and ``world=n`` (default: the bluefog world
    size if initialized), every yield is the GLOBAL batch
    ``[n, per_rank_batch, ...]`` — row r is rank r's disjoint shard, the
    layout every ``bluefog_tpu`` op and train step expects.  In
    multi-process pods pass ``rank_major=False`` and ``rank=process rank``
    to stream only the local shard.

    Yielded arrays are copies owned by the caller (slot buffers are
    recycled as soon as the next batch is requested).
    """

    def __init__(self, fields: Sequence[np.ndarray], batch_size: int,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False, rank: int = 0,
                 world: Optional[int] = None, rank_major: bool = False,
                 num_workers: int = 2, prefetch_depth: int = 3,
                 transform=None, use_native: Optional[bool] = None):
        from bluefog_tpu import native

        self._fields = [np.ascontiguousarray(f) for f in fields]
        n = self._fields[0].shape[0]
        if world is None:
            from bluefog_tpu import api

            world = api.size() if api.is_initialized() else 1
        self.rank_major = rank_major
        self.world = world
        if rank_major:
            if rank != 0:
                raise ValueError(
                    "rank_major streams the GLOBAL batch (one process feeds "
                    "all ranks); rank must stay 0 — in a multi-process pod "
                    "use rank_major=False with rank=process rank")
            # one interleaved global stream: sampler shards inside batches
            self._sampler = DistributedSampler(
                n, rank=0, world=1, shuffle=shuffle, seed=seed,
                drop_last=drop_last)
            if batch_size % world:
                raise ValueError(
                    f"rank_major needs batch_size % world == 0, got "
                    f"{batch_size} % {world}")
        else:
            self._sampler = DistributedSampler(
                n, rank=rank, world=world, shuffle=shuffle, seed=seed,
                drop_last=drop_last)
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._transform = transform
        if use_native is None:
            use_native = native.available()
        if use_native:
            self._pipe = native.NativeBatchPipeline(
                self._fields, batch_size, depth=prefetch_depth,
                workers=num_workers)
        else:
            self._pipe = _PythonPipeline(
                self._fields, batch_size, depth=prefetch_depth)
        self.native = use_native
        self._epoch_next = 0
        self._skip_next = 0   # batches to fast-forward on the next epoch
        self._cur = None      # (epoch, batches consumed) while iterating

    @property
    def sampler(self) -> DistributedSampler:
        return self._sampler

    def __len__(self):
        per_epoch = len(self._sampler)
        if self.drop_last:
            return per_epoch // self.batch_size
        return -(-per_epoch // self.batch_size)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, ...]]:
        epoch = self._epoch_next
        self._epoch_next = epoch + 1
        skip = self._skip_next
        self._skip_next = 0
        order = self._sampler.indices(epoch)
        if self.drop_last:
            order = order[:len(order) - len(order) % self.batch_size]
        elif self.rank_major and len(order) % self.world:
            # pad by wrapping so the trailing partial batch still splits
            # into equal per-rank rows (batch_size % world == 0 and
            # len(order) % world == 0 imply count % world == 0) — same
            # pad-for-equal-shards rule as DistributedSampler
            pad = self.world - len(order) % self.world
            order = np.resize(order, len(order) + pad)  # tiles if pad > len
        # fast-forward a resumed mid-epoch position in O(1): batches are
        # consecutive chunks of ``order`` (slice AFTER trim/pad so batch
        # boundaries stay identical to the uninterrupted epoch)
        self._pipe.start_epoch(order[skip * self.batch_size:])
        self._cur = {"epoch": epoch, "batch": skip}
        consumed = skip
        while True:
            item = self._pipe.next()
            if item is None:
                break
            slot, views = item
            batch = tuple(v.copy() for v in views)
            self._pipe.release(slot)
            consumed += 1
            if self.rank_major:
                per = batch[0].shape[0] // self.world
                batch = tuple(
                    b.reshape((self.world, per) + b.shape[1:])
                    for b in batch)
            if self._transform is not None:
                batch = self._transform(*batch)
            self._cur = {"epoch": epoch, "batch": consumed}
            yield batch
        self._cur = None

    def state_dict(self) -> dict:
        """Resumable loader position: the in-progress epoch and how many of
        its batches have been yielded (0 at an epoch boundary).  Save it
        alongside the train state; after ``load_state_dict`` the next
        iteration fast-forwards to exactly that position, so a restored
        job replays the same batch stream.

        The position counts batches YIELDED by this loader — if a
        lookahead wrapper (e.g. ``device_prefetch``) sits between the
        loader and the train step, the count runs ahead of what was
        trained on; checkpoint loader state only when iterating the loader
        directly (or account for the wrapper's depth)."""
        if self._cur is not None:
            return dict(self._cur)
        # not mid-iteration: a loaded-but-not-yet-resumed position must
        # round-trip (saving right after restore is a common startup path)
        return {"epoch": self._epoch_next, "batch": self._skip_next}

    def load_state_dict(self, state: dict):
        self._epoch_next = int(state["epoch"])
        self._skip_next = int(state.get("batch", 0))

    def close(self):
        self._pipe.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def device_prefetch(iterator, sharding=None, depth: int = 2):
    """Move batches to device ``depth`` steps ahead of the consumer.

    Wraps any host-batch iterator; each element (tuple of arrays) is
    ``jax.device_put`` (with ``sharding`` if given) while the previous
    batch is still being consumed, overlapping H2D transfer with compute.

    NOTE: this wrapper pulls ``depth`` batches ahead, so a wrapped
    ``DataLoader``'s ``state_dict()`` counts batches the trainer has not
    consumed yet — see ``DataLoader.state_dict``.
    """
    import collections

    import jax

    buf = collections.deque()

    def put(batch):
        if sharding is None:
            return jax.device_put(batch)
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch)

    it = iter(iterator)
    try:
        for _ in range(depth):
            buf.append(put(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(put(next(it)))
        except StopIteration:
            pass
        yield out
