"""Version compatibility shims for the jax API surface this package uses.

The codebase targets the modern ``jax.shard_map(f, mesh=..., in_specs=...,
out_specs=..., check_vma=...)`` entry point.  Older jax releases (< 0.5)
ship the same functionality as ``jax.experimental.shard_map.shard_map``
with the replication check spelled ``check_rep``.  Importing this module
(done unconditionally from ``bluefog_tpu.__init__``) installs a
signature-adapting alias so every call site can use the one modern
spelling regardless of the installed jax.

Nothing here changes behavior on a jax that already has ``jax.shard_map``.
"""

from __future__ import annotations

import jax

__all__ = ["install"]


def _make_legacy_shard_map():
    from jax.experimental.shard_map import shard_map as _legacy

    # NOTE: installed onto the PROCESS-GLOBAL jax module, so a cohosted
    # library that feature-detects `hasattr(jax, "shard_map")` will see
    # it too — accept mesh positionally (like the legacy function) so
    # such callers do not hit a keyword-only TypeError.
    def shard_map(f, mesh=None, *, in_specs, out_specs, check_vma=None,
                  **kwargs):
        # modern name -> legacy name; default stays the legacy default
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, **kwargs)

    return shard_map


def _axis_size(axis_name):
    """``lax.axis_size`` for jax versions that predate it: the size of a
    mapped axis is the psum of 1 over it, folded to a Python int at trace
    time via the axis env (jax.core.get_axis_env / axis_frame)."""
    from jax import core as jcore

    size = jcore.axis_frame(axis_name)  # returns the size on 0.4.x
    return getattr(size, "size", size)


def install() -> None:
    """Idempotently install the shims onto the ``jax`` module."""
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _make_legacy_shard_map()
    if not hasattr(jax.lax, "axis_size"):
        jax.lax.axis_size = _axis_size
    if not hasattr(jax, "enable_x64"):
        from jax.experimental import enable_x64 as _e64
        jax.enable_x64 = _e64


install()
