"""Topology compiler: telemetry-fed synthesis of mixing schedules scored
against a pod cost model.

The mixing topology was hand-picked from a fixed menu (ring / exp2 /
torus-exp2, machine-scored once by ``default_pod_schedule``) even though
the repo measures everything needed to do better: ``topology/torus.py``
machine-scores schedules (congestion x rounds-to-consensus) and
``observe/fleet.py`` records real per-edge traffic as
``bf_edge_bytes_total``.  This module closes the loop:

* :class:`PodSpec` generalizes ``TorusSpec``'s congestion model to a
  **heterogeneous** pod: ``machines x chips_per_machine``, where the
  machine axis rides expensive DCN links and the chip axis cheap ICI
  links, plus optional per-link cost multipliers **calibrated from a
  fleet-telemetry traffic snapshot** (``PodSpec.calibrated`` /
  ``PodSpec.from_telemetry`` route measured ``bf_edge_bytes_total``
  bytes onto the physical links and charge busy links more).  One
  round's cost is ``max over links of load(link) * cost(link)`` — the
  wall-time multiplier of the link-limited model; homogeneous costs
  recover ``round_congestion`` exactly.

* :func:`compile_topology` **searches** the space of weighted
  one-peer/multi-shift schedules, TACCL-style (sketch-guided: the
  :class:`Sketch` names candidate shift families, a period bound and a
  degree bound) with Swing-style short-cutting (arXiv:2401.09356 —
  bidirectional ``+-s`` rounds and direction-flip / shift+-1
  mutations).  Search = seeded candidate enumeration over
  circulant/torus shift families + hill-climbing mutations + **weight
  optimization per candidate** (per-round self-weight on a grid,
  row-stochastic by construction, spectral-gap objective), scored by
  ``cost_to_consensus`` extended with the heterogeneous link costs and
  pruned with the ``consensus_contraction`` bound
  ``cost >= sum(round costs)`` (rounds-to-consensus is never below one
  period), so n=128 synthesis finishes in seconds.

  Every candidate family is circulant — per torus axis or in rank
  space — so one period's contraction is evaluated in closed form over
  the frequency grid (the mixing matrices commute and are jointly
  diagonalized by the DFT; the generic ``consensus_contraction`` on
  the materialized matrices agrees to machine precision, which the
  tests assert).  The bidirectional family is why the compiler beats
  the menu: a ``+-s`` round with self-weight theta has the REAL
  frequency response ``theta + (1-theta) cos(2 pi s j / L)``, so a
  **zero-self-weight** round kills whole conjugate frequency pairs at
  congestion 1 where one-directional exp2 pays congestion ``s`` — e.g.
  on an (8, 16) pod the synthesized schedule reaches the exact average
  at total link cost 24 vs torus-exp2's 31 (DCN 4x ICI), and 12 vs 16
  even on a homogeneous torus.

* The winner is emitted as ordinary :class:`DynamicTopology` rounds
  (:class:`CompiledTopology`), which plug into
  ``optim.functional.build_train_step(schedule=...)`` unchanged, plus
  a wire-cost prediction (``predicted_collectives``) the HLO tests
  hold the real lowering to: one ``lax.ppermute`` per materialized
  shift class per round, carrying exactly the payload bytes.

Offline CLI::

    python -m bluefog_tpu.topology.compiler --machines 4 --chips 8 --emit json

No jax imports: pure host-side synthesis (trace-time / CPU-only safe).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import time
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.topology.spec import DynamicTopology
from bluefog_tpu.topology.torus import (
    TorusSpec,
    consensus_contraction,
    link_loads,
    rounds_from_contraction,
    schedule_congestion,
    torus_one_peer_schedule,
)

__all__ = [
    "PodSpec",
    "Sketch",
    "Candidate",
    "CandidateRound",
    "CompiledTopology",
    "CompiledHierarchicalTopology",
    "CompiledAllToAll",
    "candidate_contraction",
    "expand_machine_pairs",
    "materialize",
    "menu_schedules",
    "compile_topology",
    "compile_all_to_all",
    "main",
]

LinkKey = Tuple[Tuple[int, ...], int, int]


def _score_fields(congestions: Sequence[float], costs: Sequence[float],
                  sigma: float, eps: float) -> Dict[str, float]:
    """The ONE score-dict schema: built here whether the inputs come
    from the search's cached per-round metrics (``evaluate``) or the
    generic matrix machinery (:meth:`PodSpec.score`), so every
    ``CompiledTopology.report`` entry reads uniformly."""
    period = len(costs)
    r2c = rounds_from_contraction(sigma, period, eps)
    mean_cost = float(np.mean(costs)) if costs else 0.0
    return {
        "rounds_per_period": float(period),
        "mean_congestion": (float(np.mean(congestions))
                            if congestions else 0.0),
        "max_congestion": (float(np.max(congestions))
                           if congestions else 0.0),
        "mean_round_cost": mean_cost,
        "max_round_cost": float(np.max(costs)) if costs else 0.0,
        "rounds_to_consensus": r2c,
        "cost_to_consensus": mean_cost * r2c,
        "exact_average_per_period": float(sigma < 1e-12),
    }


# ------------------------------------------------------------------ #
# the pod cost model
# ------------------------------------------------------------------ #
@dataclasses.dataclass(frozen=True)
class PodSpec:
    """Physical interconnect of a pod: ``machines`` hosts in a ring of
    DCN links (torus axis 0), each holding ``chips_per_machine`` chips
    in a ring of ICI links (torus axis 1).  Rank r sits at the
    row-major coordinate, the ``create_device_mesh`` order —
    ``TorusSpec((machines, chips))`` with per-axis link costs.

    ``ici_cost`` / ``dcn_cost`` are relative time units per unit
    payload per link (the reciprocal-bandwidth ratio; the defaults say
    a DCN hop is 4x an ICI hop).  ``link_cost_overrides`` multiply
    individual links — the CALIBRATION hook: :meth:`calibrated` fills
    them from a measured per-edge traffic snapshot, so the cost model
    reflects measured, not assumed, link contention.

    A round's cost is ``max over links of load * cost`` — the
    link-limited wall-time multiplier (``round_congestion`` weighted
    by link cost; with ``ici_cost == dcn_cost == 1`` and no overrides
    the two are identical).
    """

    machines: int
    chips_per_machine: int
    ici_cost: float = 1.0
    dcn_cost: float = 4.0
    link_cost_overrides: Tuple[Tuple[LinkKey, float], ...] = ()

    def __post_init__(self):
        if self.machines < 1 or self.chips_per_machine < 1:
            raise ValueError(
                f"pod needs machines >= 1 and chips >= 1, got "
                f"{self.machines} x {self.chips_per_machine}")
        if self.ici_cost <= 0 or self.dcn_cost <= 0:
            raise ValueError("link costs must be positive")

    @property
    def axes(self) -> Tuple[int, int]:
        return (self.machines, self.chips_per_machine)

    @property
    def size(self) -> int:
        return self.machines * self.chips_per_machine

    @functools.cached_property
    def torus(self) -> TorusSpec:
        return TorusSpec(self.axes)

    @functools.cached_property
    def _overrides(self) -> Dict[LinkKey, float]:
        return dict(self.link_cost_overrides)

    def link_cost(self, key: LinkKey) -> float:
        """Cost of one unit payload crossing the directed link ``key``
        (``(node_coord, axis, sign)``, the ``link_loads`` keying)."""
        base = self.dcn_cost if key[1] == 0 else self.ici_cost
        return base * self._overrides.get(key, 1.0)

    def round_cost(self, round_or_pairs) -> float:
        """Wall-time multiplier of one exchange round: route every
        edge along dimension-ordered minimal paths, then take the most
        expensive link's ``load * cost``."""
        if isinstance(round_or_pairs, DynamicTopology):
            pairs = list(round_or_pairs.edges)
        elif isinstance(round_or_pairs, dict):
            pairs = list(round_or_pairs.items())
        else:
            pairs = list(round_or_pairs)
        loads = link_loads(pairs, self.torus)
        if not loads:
            return 0.0
        return max(load * self.link_cost(k) for k, load in loads.items())

    def schedule_cost(self, schedule: Sequence) -> Dict[str, object]:
        per_round = [self.round_cost(r) for r in schedule]
        return {
            "per_round": per_round,
            "mean": float(np.mean(per_round)) if per_round else 0.0,
            "max": float(np.max(per_round)) if per_round else 0.0,
        }

    def score(self, schedule: Sequence[DynamicTopology],
              eps: float = 1e-3,
              sigma: Optional[float] = None) -> Dict[str, float]:
        """``score_schedule`` extended with heterogeneous link costs:
        ``cost_to_consensus`` charges each round its pod cost instead
        of its bare congestion.  ``sigma`` (one period's contraction)
        may be supplied by a caller that already knows it (the
        compiler's Fourier scoring); otherwise it is measured from the
        materialized mixing matrices."""
        cong = schedule_congestion(schedule, self.torus)
        cost = self.schedule_cost(schedule)
        if sigma is None:
            sigma = consensus_contraction(schedule)
        return _score_fields(cong["per_round"], cost["per_round"],
                             sigma, eps)

    # ---------------------------------------------------------- #
    # telemetry calibration
    # ---------------------------------------------------------- #
    def traffic_link_loads(
            self, traffic: Mapping[Tuple[int, int], float],
    ) -> Dict[LinkKey, float]:
        """Route a measured per-edge traffic snapshot (``{(src, dst):
        bytes}``, the ``bf_edge_bytes_total`` shape) onto the physical
        links: per-link background bytes under the same
        dimension-ordered minimal routing the schedule cost uses.
        Edges naming ranks outside this pod mean the snapshot came
        from a differently-shaped fleet — a configuration error worth
        a loud message, not an IndexError from the router."""
        n = self.size
        bad = sorted(r for (s, d) in traffic for r in (s, d)
                     if not 0 <= r < n)
        if bad:
            raise ValueError(
                f"traffic snapshot names rank(s) {bad[:4]} outside "
                f"this {self.machines}x{self.chips_per_machine} pod "
                f"(size {n}) — was it recorded by a different fleet "
                f"shape?")
        pairs = [(s, d) for (s, d) in traffic]
        payloads = {(s, d): float(b) for (s, d), b in traffic.items()}
        return link_loads(pairs, self.torus, payloads=payloads)

    def calibrated(self, traffic: Mapping[Tuple[int, int], float],
                   contention: float = 1.0) -> "PodSpec":
        """A new ``PodSpec`` whose link costs reflect measured
        contention: link l's cost is multiplied by ``1 + contention *
        bytes_l / max_bytes`` (bytes_l = the snapshot's background
        traffic routed onto l).  A new payload on the busiest link
        queues behind the most background traffic, so the compiler is
        steered toward the links telemetry shows are quiet — the
        schedule adapts to measured, not assumed, link costs."""
        loads = self.traffic_link_loads(traffic)
        top = max(loads.values(), default=0.0)
        if top <= 0.0:
            return self
        overrides = dict(self.link_cost_overrides)
        for key, b in loads.items():
            overrides[key] = (overrides.get(key, 1.0)
                              * (1.0 + contention * b / top))
        return dataclasses.replace(
            self, link_cost_overrides=tuple(sorted(overrides.items())))

    @classmethod
    def from_telemetry(cls, machines: int, chips_per_machine: int,
                       registry=None, contention: float = 1.0,
                       link: Optional[str] = None,
                       **kwargs) -> "PodSpec":
        """Build a pod spec calibrated from the LIVE fleet-telemetry
        traffic counters: reads the ``bf_edge_bytes_total{src,dst}``
        family out of the metrics registry
        (:func:`bluefog_tpu.observe.fleet.traffic_snapshot`) and
        routes it into per-link cost multipliers.  With no recorded
        traffic this is the plain (uncalibrated) spec.

        ``link`` filters the snapshot to one billed leg ("dcn"/"ici" —
        the per-leg labels a hierarchical step records): calibrating a
        HIERARCHICAL synthesis from ``link="dcn"`` routes only the
        inter-machine bytes onto the cost model, so cheap intra-machine
        chatter never masquerades as DCN load."""
        from bluefog_tpu.observe.fleet import traffic_snapshot

        base = cls(machines, chips_per_machine, **kwargs)
        return base.calibrated(traffic_snapshot(registry, link=link),
                               contention=contention)


# ------------------------------------------------------------------ #
# the sketch: candidate space bounds + search budget
# ------------------------------------------------------------------ #
@dataclasses.dataclass(frozen=True)
class Sketch:
    """TACCL-style communication sketch: the human-supplied outline
    that bounds the synthesis space instead of hand-picking the
    schedule.  ``families`` seed the enumeration (torus-axis and
    rank-space circulant shift families); ``max_period`` bounds the
    schedule length, ``max_degree`` the per-round in-degree (1 =
    strictly one-peer, 2 admits the Swing-style bidirectional ``+-s``
    rounds); ``mutation_rounds`` bounds the hill-climbing generations
    and ``weight_grid``/``weight_sweeps`` the per-round self-weight
    optimization."""

    families: Tuple[str, ...] = ("torus_exp2", "torus_exp2_rev",
                                 "torus_sym", "single_hop",
                                 "logical_exp2", "ring")
    max_period: int = 12
    max_degree: int = 2
    mutation_rounds: int = 3
    weight_sweeps: int = 2
    weight_grid: Tuple[float, ...] = tuple(k / 16.0 for k in range(16))

    def __post_init__(self):
        if self.max_period < 1 or self.max_degree < 1:
            raise ValueError("sketch bounds must be >= 1")
        if not self.weight_grid or not all(
                0.0 <= t < 1.0 for t in self.weight_grid):
            raise ValueError("weight grid must lie in [0, 1)")


@dataclasses.dataclass(frozen=True)
class CandidateRound:
    """One round of a candidate: a set of circulant shifts applied
    simultaneously, plus the per-rank self-weight ``theta``.  The
    remaining mass ``1 - theta`` splits equally across the shifts —
    row-stochastic by construction.  ``shifts`` entries are ``(axis,
    shift)`` with ``axis`` a torus axis (torus-space candidates) or
    ``None`` (rank-space circulant over Z_n)."""

    shifts: Tuple[Tuple[Optional[int], int], ...]
    theta: float = 0.5


@dataclasses.dataclass(frozen=True)
class Candidate:
    """A full candidate schedule in one shift space ("torus" or
    "rank"); both spaces are commutative circulant families, so the
    period contraction has the closed Fourier form
    :func:`candidate_contraction` evaluates."""

    name: str
    space: str  # "torus" | "rank"
    rounds: Tuple[CandidateRound, ...]


def _round_factor_base(rnd: CandidateRound, axes: Sequence[int],
                       space: str) -> np.ndarray:
    """The round's shift response G with F(theta) = theta +
    (1 - theta) * G, over the full frequency grid (rank space: Z_n;
    torus space: Z_L0 x Z_L1 ...).  Independent of theta, so weight
    optimization re-evaluates F from a cached G."""
    n = int(np.prod(axes))
    if space == "rank":
        js = np.arange(n)
        terms = [np.exp(2j * np.pi * (s % n) * js / n)
                 for (_, s) in rnd.shifts]
    else:
        grids = np.meshgrid(*[np.arange(L) for L in axes], indexing="ij")
        terms = [np.exp(2j * np.pi * (s % axes[a]) * grids[a] / axes[a])
                 for (a, s) in rnd.shifts]
    return np.mean(terms, axis=0)


def _sigma_from_factors(factors: Sequence[np.ndarray],
                        thetas: Sequence[float]) -> float:
    """max over non-DC frequencies of |prod_t (theta_t +
    (1-theta_t) G_t)| — one period's contraction, exactly (the rounds
    commute and are diagonalized by the same DFT)."""
    prod = np.ones_like(factors[0])
    for G, th in zip(factors, thetas):
        prod = prod * (th + (1.0 - th) * G)
    mags = np.abs(prod).reshape(-1)
    mags[0] = 0.0  # DC: row sums are 1 by construction
    return float(mags.max())


def candidate_contraction(cand: Candidate,
                          axes: Sequence[int]) -> float:
    """One period's spectral contraction of a candidate, in closed
    form over the frequency grid — equal to
    ``consensus_contraction(materialize(cand, axes))`` to machine
    precision (tested), at O(period * n) instead of O(period * n^3)."""
    factors = [_round_factor_base(r, axes, cand.space)
               for r in cand.rounds]
    return _sigma_from_factors(factors, [r.theta for r in cand.rounds])


def _shift_dst(src: int, axis: Optional[int], s: int,
               axes: Sequence[int], spec: TorusSpec, n: int) -> int:
    """Destination of one shift from ``src`` — the ONE place the
    rank-space vs torus-space mapping lives, so the search's routing
    cost and the materialized schedule can never disagree."""
    if axis is None:
        return (src + s) % n
    c = list(spec.coord(src))
    c[axis] = (c[axis] + s) % axes[axis]
    return spec.rank(c)


def _round_pairs(rnd: CandidateRound, axes: Sequence[int],
                 space: str) -> List[Tuple[int, int]]:
    """The (src, dst) edges one candidate round exchanges — exactly
    the materialized round's edge set (self-maps dropped; duplicate
    shifts landing on the same dst merge); theta only moves weights,
    never edges."""
    return list(materialize_round(rnd, axes, space).edges)


def materialize_round(rnd: CandidateRound, axes: Sequence[int],
                      space: str) -> DynamicTopology:
    """Emit one candidate round as an ordinary ``DynamicTopology``:
    each shift contributes weight ``(1 - theta)/k`` on its edge;
    shifts that collapse to the same (src, dst) (e.g. +-1 on a
    length-2 axis) accumulate, and shifts that collapse to self fold
    into the self-weight — exactly matching the Fourier response."""
    n = int(np.prod(axes))
    spec = TorusSpec(tuple(axes))
    w = (1.0 - rnd.theta) / len(rnd.shifts)
    edges: Dict[Tuple[int, int], float] = {}
    selfs = [rnd.theta] * n
    for (axis, s) in rnd.shifts:
        for src in range(n):
            dst = _shift_dst(src, axis if space != "rank" else None,
                             s, axes, spec, n)
            if dst == src:
                selfs[src] += w
            else:
                edges[(src, dst)] = edges.get((src, dst), 0.0) + w
    return DynamicTopology.from_edges(n, edges, selfs)


def materialize(cand: Candidate,
                axes: Sequence[int]) -> List[DynamicTopology]:
    """The candidate as a ready-to-train dynamic schedule."""
    return [materialize_round(r, axes, cand.space) for r in cand.rounds]


# ------------------------------------------------------------------ #
# seeds: the sketch's shift families
# ------------------------------------------------------------------ #
def _norm_shift(s: int, L: int) -> Optional[int]:
    s = s % L
    return None if s == 0 else s


def _seed_candidates(pod: PodSpec, sketch: Sketch) -> List[Candidate]:
    axes = pod.axes
    n = pod.size
    out: List[Candidate] = []

    def add(name: str, space: str, rounds: List[CandidateRound]):
        rounds = [r for r in rounds if r.shifts]
        if not rounds or len(rounds) > sketch.max_period:
            return
        if any(len(r.shifts) > sketch.max_degree for r in rounds):
            return
        out.append(Candidate(name, space, tuple(rounds)))

    def axis_rounds(direction: int) -> List[CandidateRound]:
        rounds = []
        for a, L in enumerate(axes):
            for k in range(max(0, int(math.log2(L)) if L > 1 else 0)):
                s = _norm_shift(direction * (2 ** k), L)
                if s is not None:
                    rounds.append(CandidateRound(((a, s),), 0.5))
        return rounds

    fams = set(sketch.families)
    if "torus_exp2" in fams:
        add("torus_exp2", "torus", axis_rounds(+1))
    if "torus_exp2_rev" in fams:
        add("torus_exp2_rev", "torus", axis_rounds(-1))
    if "torus_sym" in fams and sketch.max_degree >= 2:
        # Swing-style bidirectional halving: per axis, one +-1 round at
        # theta=1/2 kills the top frequency, then zero-self-weight
        # +-2^k rounds kill the remaining conjugate pairs — exact
        # average per period at lower congestion than exp2.
        rounds = []
        for a, L in enumerate(axes):
            if L < 2:
                continue
            one = _norm_shift(1, L)
            back = _norm_shift(-1, L)
            first = ((a, one),) if back in (None, one) else (
                (a, one), (a, back))
            rounds.append(CandidateRound(first, 0.5))
            for k in range(int(math.log2(L)) - 1):
                s, b = _norm_shift(2 ** k, L), _norm_shift(-(2 ** k), L)
                shifts = ((a, s),) if b in (None, s) else ((a, s), (a, b))
                rounds.append(CandidateRound(shifts, 0.0))
        add("torus_sym", "torus", rounds)
    if "single_hop" in fams:
        rounds = []
        for a, L in enumerate(axes):
            if L < 2:
                continue
            rounds.append(CandidateRound(((a, 1),), 0.5))
            if L > 2:
                rounds.append(CandidateRound(((a, L - 1),), 0.5))
        add("single_hop", "torus", rounds)
    if "logical_exp2" in fams:
        rounds = [CandidateRound(((None, 2 ** k),), 0.5)
                  for k in range(max(1, int(math.ceil(math.log2(n)))))
                  if 2 ** k < n]
        add("logical_exp2", "rank", rounds)
    if "ring" in fams:
        add("ring", "rank", [CandidateRound(((None, 1),), 0.5)])
    return out


# ------------------------------------------------------------------ #
# mutations: Swing short-cutting on the candidate structure
# ------------------------------------------------------------------ #
def _mutants(cand: Candidate, axes: Sequence[int],
             sketch: Sketch) -> List[Candidate]:
    """Single-point structural mutations: direction flips, shift +-1
    short-cuts, symmetrize (add the opposite shift), desymmetrize
    (drop one shift of a multi-shift round), and round removal — the
    neighborhood the hill-climber explores each generation."""
    n = int(np.prod(axes))

    def mod_of(axis: Optional[int]) -> int:
        return n if axis is None else axes[axis]

    out: List[Candidate] = []

    def emit(tag: str, rounds: List[CandidateRound]):
        rounds = [r for r in rounds if r.shifts]
        if not rounds or len(rounds) > sketch.max_period:
            return
        for r in rounds:
            if len(r.shifts) > sketch.max_degree:
                return
            if len(set(r.shifts)) != len(r.shifts):
                return
        out.append(Candidate(f"{cand.name}~{tag}", cand.space,
                             tuple(rounds)))

    rounds = list(cand.rounds)
    for t, rnd in enumerate(rounds):
        for k, (axis, s) in enumerate(rnd.shifts):
            L = mod_of(axis)
            variants = []
            flip = _norm_shift(-s, L)
            if flip is not None and flip != s:
                variants.append(("flip", flip))
            for d in (-1, +1):
                sc = _norm_shift(s + d, L)
                if sc is not None and sc != s:
                    variants.append((f"sc{d:+d}", sc))
            for tag, ns in variants:
                shifts = list(rnd.shifts)
                shifts[k] = (axis, ns)
                emit(f"r{t}{tag}", rounds[:t]
                     + [CandidateRound(tuple(shifts), rnd.theta)]
                     + rounds[t + 1:])
            if len(rnd.shifts) > 1:
                shifts = rnd.shifts[:k] + rnd.shifts[k + 1:]
                emit(f"r{t}drop{k}", rounds[:t]
                     + [CandidateRound(shifts, rnd.theta)]
                     + rounds[t + 1:])
        if (len(rnd.shifts) < sketch.max_degree
                and len(rnd.shifts) == 1):
            (axis, s) = rnd.shifts[0]
            opp = _norm_shift(-s, mod_of(axis))
            if opp is not None and opp != s:
                emit(f"r{t}sym", rounds[:t]
                     + [CandidateRound(((axis, s), (axis, opp)),
                                       rnd.theta)]
                     + rounds[t + 1:])
        if len(rounds) > 1:
            emit(f"r{t}rm", rounds[:t] + rounds[t + 1:])
    return out


# ------------------------------------------------------------------ #
# per-candidate weight optimization (spectral-gap objective)
# ------------------------------------------------------------------ #
def _optimize_weights(cand: Candidate, axes: Sequence[int],
                      sketch: Sketch) -> Tuple[Candidate, float]:
    """Coordinate descent on the per-round self-weights over the
    sketch's grid, minimizing one period's contraction (the
    spectral-gap objective).  Row-stochasticity is structural (theta
    in [0, 1), equal split of the rest).  Cheap: each evaluation is a
    cached-factor product over the frequency grid, so the grid search
    finds the exact killers (theta = 0 and 1/2) the closed-form
    constructions use."""
    factors = [_round_factor_base(r, axes, cand.space)
               for r in cand.rounds]
    thetas = [r.theta for r in cand.rounds]
    sigma = _sigma_from_factors(factors, thetas)
    for _ in range(sketch.weight_sweeps):
        improved = False
        for t in range(len(thetas)):
            best_th, best_sigma = thetas[t], sigma
            for th in sketch.weight_grid:
                if th == thetas[t]:
                    continue
                trial = list(thetas)
                trial[t] = th
                s = _sigma_from_factors(factors, trial)
                if s < best_sigma - 1e-15:
                    best_th, best_sigma = th, s
            if best_th != thetas[t]:
                thetas[t], sigma = best_th, best_sigma
                improved = True
        if not improved:
            break
    rounds = tuple(CandidateRound(r.shifts, th)
                   for r, th in zip(cand.rounds, thetas))
    return Candidate(cand.name, cand.space, rounds), sigma


# ------------------------------------------------------------------ #
# the compiled artifact
# ------------------------------------------------------------------ #
@dataclasses.dataclass
class CompiledTopology:
    """A synthesized mixing schedule plus everything needed to audit
    it: the winning ``DynamicTopology`` rounds (feed ``schedule`` to
    ``build_train_step(schedule=...)`` unchanged), the pod-cost score,
    the per-candidate report the choice was made from, and search
    statistics.  ``predicted_collectives`` states the wire cost the
    cost model charged — the claim the HLO tests hold the real
    lowering to."""

    schedule: List[DynamicTopology]
    score: Dict[str, float]
    name: str
    pod: PodSpec
    report: Dict[str, Dict[str, float]]
    search: Dict[str, float]

    def predicted_collectives(self, payload_bytes: float) -> Dict:
        """The exact collective lowering the schedule implies, each
        permute carrying the full per-rank payload (weights are traced
        operands — a declared edge always moves bytes).  Mirrors
        ``collectives.neighbor_allreduce``'s class-fusion rule: an
        in-degree-1 round (every src and dst unique across ALL shift
        classes — e.g. a wrapping one-peer rotation that decomposes
        into two partial permutations) fuses into ONE
        ``lax.ppermute``; multi-shift rounds issue one per class."""
        per_round = []
        for r in self.schedule:
            pairs = [p for cls in r.shift_classes for p in cls.perm]
            srcs = [s for s, _ in pairs]
            dsts = [d for _, d in pairs]
            fused = (len(set(srcs)) == len(srcs)
                     and len(set(dsts)) == len(dsts))
            per_round.append({
                "permutes": 1 if fused else len(r.shift_classes),
                "bytes_per_permute": float(payload_bytes),
            })
        return {
            "permutes_per_period": sum(r["permutes"] for r in per_round),
            "bytes_per_period": float(sum(
                r["permutes"] * r["bytes_per_permute"]
                for r in per_round)),
            "per_round": per_round,
        }

    def as_json(self) -> Dict:
        """JSON-ready emission (the CLI's ``--emit json`` payload)."""
        return {
            "pod": {
                "machines": self.pod.machines,
                "chips_per_machine": self.pod.chips_per_machine,
                "ici_cost": self.pod.ici_cost,
                "dcn_cost": self.pod.dcn_cost,
                "calibrated_links": len(self.pod.link_cost_overrides),
            },
            "winner": self.name,
            "score": self.score,
            "report": self.report,
            "search": self.search,
            "schedule": [
                {
                    "edges": [[int(s), int(d), float(w)] for (s, d), w in
                              zip(r.edges, r.edge_weight_values)],
                    "self_weights": [float(w)
                                     for w in r.self_weight_values],
                }
                for r in self.schedule
            ],
        }


# ------------------------------------------------------------------ #
# hierarchical synthesis: exact ICI reduce inside the machine,
# decentralized mixing only across DCN
# ------------------------------------------------------------------ #
def expand_machine_pairs(pairs: Sequence[Tuple[int, int]],
                         local_size: int) -> List[Tuple[int, int]]:
    """Expand MACHINE-level edges to the RANK-level counterpart pairs
    the hierarchical exchange actually wires (``collectives.
    hierarchical_neighbor_allreduce``): local rank ``j`` of machine
    ``ms`` sends to local rank ``j`` of machine ``md``.  Pure host-side
    mirror of the jax implementation's expansion, so the cost model and
    the HLO predictions can never disagree with the lowering."""
    L = int(local_size)
    return [(ms * L + j, md * L + j)
            for (ms, md) in pairs for j in range(L)]


def _ici_reduce_cost(pod: PodSpec) -> Tuple[float, float]:
    """(congestion, cost) of the intra-machine exact-mean leg: a ring
    allreduce of the full payload over each machine's ``L`` chips puts
    ``2 (L - 1) / L`` payload units on every ICI link (reduce-scatter +
    all-gather), priced at the most expensive ICI link's calibrated
    cost.  ``L == 1`` machines have no ICI leg."""
    L = pod.chips_per_machine
    if L < 2:
        return 0.0, 0.0
    load = 2.0 * (L - 1) / L
    worst = max(pod.link_cost((pod.torus.coord(r), 1, sign))
                for r in range(pod.size) for sign in (+1, -1))
    return load, load * worst


def _machine_pod(pod: PodSpec) -> PodSpec:
    """The inter-machine graph as a smaller pod for the existing sketch
    search: ``machines x 1``, DCN-priced axis 0 only.  Calibrated
    DCN-link overrides carry over machine-wise (the max over the
    machine's chip lanes — a congested lane throttles the whole
    machine exchange, since the counterpart expansion pins every lane
    into the same round)."""
    agg: Dict[LinkKey, float] = {}
    for (coord, axis, sign), mult in pod.link_cost_overrides:
        if axis != 0:
            continue  # ICI overrides are priced by _ici_reduce_cost
        key = ((coord[0], 0), 0, sign)
        agg[key] = max(agg.get(key, 1.0), mult)
    return PodSpec(pod.machines, 1, ici_cost=pod.ici_cost,
                   dcn_cost=pod.dcn_cost,
                   link_cost_overrides=tuple(sorted(agg.items())))


def _hierarchical_score(pod: PodSpec,
                        machine_schedule: Sequence[DynamicTopology],
                        eps: float = 1e-3) -> Dict[str, float]:
    """Full-pod score of a two-level schedule, same ``cost_to_consensus``
    schema as the flat scorer: each round pays the ICI reduce leg PLUS
    the DCN leg of its counterpart-expanded machine edges (max link
    load x calibrated cost, dimension-ordered routing — identical
    machinery to the flat rounds it competes against).

    Contraction is the MACHINE schedule's: the expanded round mixes by
    ``kron(W_machine, J_L / L)``, whose non-DC spectrum is the machine
    matrix's non-DC spectrum plus exact zeros (the intra-machine modes
    die in the first exact mean), so rounds-to-consensus is governed by
    the inter-machine mixing alone."""
    L = pod.chips_per_machine
    ici_cong, ici_cost = _ici_reduce_cost(pod)
    congs, costs = [], []
    for r in machine_schedule:
        pairs = expand_machine_pairs(list(r.edges), L)
        loads = link_loads(pairs, pod.torus)
        dcn_cong = max(loads.values(), default=0.0)
        dcn_cost = max((load * pod.link_cost(k)
                        for k, load in loads.items()), default=0.0)
        congs.append(max(ici_cong, dcn_cong))
        costs.append(ici_cost + dcn_cost)
    sigma = consensus_contraction(machine_schedule)
    return _score_fields(congs, costs, sigma, eps)


@dataclasses.dataclass
class CompiledHierarchicalTopology:
    """A synthesized TWO-LEVEL schedule: ``local_size`` names the exact
    intra-machine reduce (the ``axis_index_groups`` width) and
    ``machine_schedule`` the decentralized inter-machine rounds — feed
    ``build_train_step(schedule=machine_schedule,
    hierarchical=local_size)`` unchanged.  ``score`` is the full-pod
    ``cost_to_consensus`` (:func:`_hierarchical_score`);
    ``predicted_collectives`` states the per-round lowering the HLO
    tests hold the real program to: exactly ONE grouped all-reduce
    (the ICI leg) plus the machine permutes, each permute carrying the
    full payload across DCN."""

    local_size: int
    machine_schedule: List[DynamicTopology]
    score: Dict[str, float]
    name: str
    pod: PodSpec
    report: Dict[str, Dict[str, float]]
    search: Dict[str, float]

    @property
    def schedule(self) -> List[DynamicTopology]:
        """Alias: the specs a train step consumes (machine-level)."""
        return self.machine_schedule

    def predicted_collectives(self, payload_bytes: float) -> Dict:
        """Per round: 1 grouped all-reduce over every machine's chips
        plus the machine-class permutes (the flat class-fusion rule
        applied at machine level — the counterpart expansion preserves
        in-degree-1-ness, so a fused machine round is one
        ``lax.ppermute`` on the wire)."""
        per_round = []
        for r in self.machine_schedule:
            pairs = [p for cls in r.shift_classes for p in cls.perm]
            srcs = [s for s, _ in pairs]
            dsts = [d for _, d in pairs]
            fused = (len(set(srcs)) == len(srcs)
                     and len(set(dsts)) == len(dsts))
            per_round.append({
                "all_reduces": 1,
                "permutes": 1 if fused else len(r.shift_classes),
                "bytes_per_permute": float(payload_bytes),
            })
        return {
            "permutes_per_period": sum(r["permutes"] for r in per_round),
            "bytes_per_period": float(sum(
                r["permutes"] * r["bytes_per_permute"]
                for r in per_round)),
            "all_reduces_per_period": len(per_round),
            "all_reduce_groups": self.pod.machines,
            "all_reduce_group_size": self.local_size,
            "bytes_per_all_reduce": float(payload_bytes),
            "per_round": per_round,
        }

    def as_json(self) -> Dict:
        return {
            "pod": {
                "machines": self.pod.machines,
                "chips_per_machine": self.pod.chips_per_machine,
                "ici_cost": self.pod.ici_cost,
                "dcn_cost": self.pod.dcn_cost,
                "calibrated_links": len(self.pod.link_cost_overrides),
            },
            "winner": self.name,
            "local_size": self.local_size,
            "score": self.score,
            "report": self.report,
            "search": self.search,
            "machine_schedule": [
                {
                    "edges": [[int(s), int(d), float(w)] for (s, d), w in
                              zip(r.edges, r.edge_weight_values)],
                    "self_weights": [float(w)
                                     for w in r.self_weight_values],
                }
                for r in self.machine_schedule
            ],
        }


# ------------------------------------------------------------------ #
# all-to-all schedule synthesis (MoE expert dispatch)
# ------------------------------------------------------------------ #
# An all-to-all moves a DISTINCT shard along every (src, dst) pair —
# n * (n - 1) directed transfers — so the schedule is not a mixing
# matrix but a PARTITION of the n - 1 nonzero torus shifts into
# rounds: round t applies its shifts simultaneously, each rank sending
# the shard addressed to its shift image (arxiv 2309.13541's
# shift-class decomposition, searched under the same heterogeneous
# PodSpec cost model as the mixing candidates).  Every shift appears
# exactly once across the period, so one period completes the
# dispatch; the objective is the SUM of per-round max-link-load costs
# ("cost to dispatch"), not a contraction rate.


def _a2a_shifts(pod: PodSpec) -> List[Tuple[int, int]]:
    """Every nonzero torus shift ``(dm, dc)`` — one per (src, dst)
    offset class; the unit of scheduling."""
    M, L = pod.axes
    return [(dm, dc) for dm in range(M) for dc in range(L)
            if (dm, dc) != (0, 0)]


def _a2a_shift_pairs(shift: Tuple[int, int],
                     pod: PodSpec) -> List[Tuple[int, int]]:
    """The n (src, dst) pairs one torus shift moves.  Distinct shifts
    send a given src to distinct dsts, so a multi-shift round's pair
    list has duplicate SRCS but never duplicate (src, dst) entries —
    the pair-list ``link_loads`` form bills every one."""
    M, L = pod.axes
    spec = pod.torus
    dm, dc = shift
    out = []
    for src in range(pod.size):
        m, c = spec.coord(src)
        out.append((src, spec.rank(((m + dm) % M, (c + dc) % L))))
    return out


def _a2a_round_topology(shifts: Sequence[Tuple[int, int]],
                        pod: PodSpec) -> DynamicTopology:
    """One a2a round as an ordinary ``DynamicTopology`` (unit edge
    weights, zero self-weights — a2a rounds move shards, they don't
    average).  Safe by construction: within a rank-space shift class,
    srcs are unique (two torus shifts sharing a class delta cannot
    share a src — same src + same delta would be the same dst, and
    distinct shifts have distinct dsts), so ``shift_classes`` always
    decomposes into partial permutations."""
    edges = {p: 1.0 for sh in shifts for p in _a2a_shift_pairs(sh, pod)}
    return DynamicTopology.from_edges(pod.size, edges,
                                      [0.0] * pod.size)


@dataclasses.dataclass
class CompiledAllToAll:
    """A synthesized all-to-all dispatch schedule plus its audit
    surface: ``schedule`` holds one ``DynamicTopology`` per round
    (feed to ``moe.dispatch.dispatch_plan`` unchanged),
    ``shifts_per_round`` the torus shifts each round carries, and
    ``score`` the cost-to-dispatch against the naive baselines.
    ``predicted_collectives`` states the exact wire lowering — the
    claim the HLO tests hold ``moe.dispatch.all_to_all_dispatch`` to,
    permute-for-permute and byte-for-byte."""

    schedule: List[DynamicTopology]
    shifts_per_round: List[Tuple[Tuple[int, int], ...]]
    score: Dict[str, float]
    name: str
    pod: PodSpec
    report: Dict[str, Dict[str, float]]
    search: Dict[str, float]

    def predicted_collectives(self, payload_bytes: float) -> Dict:
        """Same fusion rule as the mixing schedules (and as the
        dispatch implementation): a round whose union pair list has
        all-unique srcs AND dsts lowers to ONE ``lax.ppermute``;
        otherwise one per rank-space shift class, each carrying the
        full per-destination shard payload."""
        per_round = []
        for r in self.schedule:
            pairs = [p for cls in r.shift_classes for p in cls.perm]
            srcs = [s for s, _ in pairs]
            dsts = [d for _, d in pairs]
            fused = (len(set(srcs)) == len(srcs)
                     and len(set(dsts)) == len(dsts))
            per_round.append({
                "permutes": 1 if fused else len(r.shift_classes),
                "bytes_per_permute": float(payload_bytes),
            })
        return {
            "permutes_per_period": sum(r["permutes"] for r in per_round),
            "bytes_per_period": float(sum(
                r["permutes"] * r["bytes_per_permute"]
                for r in per_round)),
            "per_round": per_round,
        }

    def as_json(self) -> Dict:
        return {
            "pod": {
                "machines": self.pod.machines,
                "chips_per_machine": self.pod.chips_per_machine,
                "ici_cost": self.pod.ici_cost,
                "dcn_cost": self.pod.dcn_cost,
                "calibrated_links": len(self.pod.link_cost_overrides),
            },
            "winner": self.name,
            "score": self.score,
            "report": self.report,
            "search": self.search,
            "shifts_per_round": [
                [[int(dm), int(dc)] for (dm, dc) in shifts]
                for shifts in self.shifts_per_round
            ],
            "schedule": [
                {
                    "edges": [[int(s), int(d), float(w)] for (s, d), w in
                              zip(r.edges, r.edge_weight_values)],
                    "self_weights": [float(w)
                                     for w in r.self_weight_values],
                }
                for r in self.schedule
            ],
        }


def naive_all_to_all_cost(pod: PodSpec) -> float:
    """The topology-UNAWARE baseline: ``lax.all_to_all``'s linear
    rank-ring decomposition — n - 1 sequential rank-space shift
    rounds, each priced by the same routing machinery.  Rank shifts
    straddle the machine boundary (a +1 rank shift is mostly ICI plus
    a DCN wrap), so every round pays the DCN lane even when most of
    its traffic is intra-machine — the waste the compiled schedule
    exists to remove."""
    n = pod.size
    return float(sum(
        pod.round_cost([(r, (r + s) % n) for r in range(n)])
        for s in range(1, n)))


def one_shot_all_to_all_cost(pod: PodSpec) -> float:
    """Cost of issuing EVERY pair in one round — the congestion
    reference: no schedule can beat the busiest link's total demand,
    so this bounds cost_to_dispatch from below."""
    n = pod.size
    pairs = [(s, d) for s in range(n) for d in range(n) if s != d]
    return float(pod.round_cost(pairs))


def compile_all_to_all(pod: PodSpec, sketch: Optional[Sketch] = None,
                       verbose: bool = False) -> CompiledAllToAll:
    """Synthesize the all-to-all dispatch schedule for ``pod``: pack
    the n - 1 nonzero torus shifts into rounds of at most
    ``sketch.max_degree`` shifts, minimizing the summed per-round
    max-link cost.  Two seeds — a greedy bin-pack (expensive shifts
    anchor their own rounds, each remaining shift joins the round it
    inflates least) and the inverse pairing ``(dm, dc)`` with
    ``(-dm, -dc)`` (bidirectional rounds fill both DCN directions at
    once) — then hill-climbing by single-shift moves and pair swaps,
    every evaluation served by a frozenset-keyed round-cost cache.
    The winner emits as ``DynamicTopology`` rounds the MoE dispatch
    consumes directly."""
    sketch = sketch or Sketch()
    if pod.size < 2:
        raise ValueError("all-to-all needs a pod of size >= 2")
    t0 = time.perf_counter()
    shifts = _a2a_shifts(pod)
    pair_cache = {sh: _a2a_shift_pairs(sh, pod) for sh in shifts}
    cost_cache: Dict[frozenset, float] = {}
    stats = {"evaluations": 0}

    def round_cost(group) -> float:
        key = frozenset(group)
        if not key:
            return 0.0
        c = cost_cache.get(key)
        if c is None:
            stats["evaluations"] += 1
            pairs = [p for sh in key for p in pair_cache[sh]]
            c = cost_cache[key] = pod.round_cost(pairs)
        return c

    def total(rounds) -> float:
        return sum(round_cost(r) for r in rounds)

    def greedy_seed() -> List[set]:
        order = sorted(shifts, key=lambda sh: -round_cost({sh}))
        rounds: List[set] = []
        for sh in order:
            best_i, best_delta = None, round_cost({sh})
            for i, r in enumerate(rounds):
                if len(r) >= sketch.max_degree:
                    continue
                delta = round_cost(r | {sh}) - round_cost(r)
                if delta < best_delta - 1e-12:
                    best_i, best_delta = i, delta
            if best_i is None:
                rounds.append({sh})
            else:
                rounds[best_i].add(sh)
        return rounds

    def inverse_seed() -> List[set]:
        M, L = pod.axes
        rounds, used = [], set()
        for sh in shifts:
            if sh in used:
                continue
            inv = ((M - sh[0]) % M, (L - sh[1]) % L)
            if (sketch.max_degree >= 2 and inv != sh
                    and inv not in used):
                rounds.append({sh, inv})
                used |= {sh, inv}
            else:
                rounds.append({sh})
                used.add(sh)
        return rounds

    def climb(rounds: List[set]) -> List[set]:
        for _ in range(max(1, sketch.mutation_rounds) * 4):
            improved = False
            # single-shift moves
            for i in range(len(rounds)):
                for sh in sorted(rounds[i]):
                    base = round_cost(rounds[i])
                    rest = round_cost(rounds[i] - {sh})
                    for j in range(len(rounds)):
                        if (j == i
                                or len(rounds[j]) >= sketch.max_degree):
                            continue
                        delta = (rest + round_cost(rounds[j] | {sh})
                                 - base - round_cost(rounds[j]))
                        if delta < -1e-12:
                            rounds[i].discard(sh)
                            rounds[j].add(sh)
                            improved = True
                            break
            rounds = [r for r in rounds if r]
            # pair swaps
            for i in range(len(rounds)):
                for j in range(i + 1, len(rounds)):
                    base = round_cost(rounds[i]) + round_cost(rounds[j])
                    done = False
                    for a in sorted(rounds[i]):
                        for b in sorted(rounds[j]):
                            ni = (rounds[i] - {a}) | {b}
                            nj = (rounds[j] - {b}) | {a}
                            if (round_cost(ni) + round_cost(nj)
                                    < base - 1e-12):
                                rounds[i], rounds[j] = ni, nj
                                improved = done = True
                                break
                        if done:
                            break
            if not improved:
                break
        return [r for r in rounds if r]

    seeds = {"greedy": greedy_seed(), "inverse": inverse_seed()}
    report: Dict[str, Dict[str, float]] = {}
    best_name, best_rounds, best_cost = None, None, float("inf")
    for name, rounds in seeds.items():
        report[f"seed:{name}"] = {
            "cost_to_dispatch": total(rounds),
            "rounds_per_period": float(len(rounds)),
        }
        climbed = climb([set(r) for r in rounds])
        c = total(climbed)
        report[f"climbed:{name}"] = {
            "cost_to_dispatch": c,
            "rounds_per_period": float(len(climbed)),
        }
        if c < best_cost - 1e-12:
            best_name, best_rounds, best_cost = name, climbed, c

    assert best_rounds is not None
    # deterministic emission order: cheap rounds first, ties by shifts
    ordered = sorted((tuple(sorted(r)) for r in best_rounds),
                     key=lambda r: (round_cost(set(r)), r))
    schedule = [_a2a_round_topology(r, pod) for r in ordered]
    costs = [round_cost(set(r)) for r in ordered]
    naive = naive_all_to_all_cost(pod)
    one_shot = one_shot_all_to_all_cost(pod)
    score = {
        "rounds_per_period": float(len(ordered)),
        "mean_round_cost": float(np.mean(costs)) if costs else 0.0,
        "max_round_cost": float(np.max(costs)) if costs else 0.0,
        "cost_to_dispatch": float(best_cost),
        "naive_linear_cost": naive,
        "one_shot_cost": one_shot,
        "compiled_advantage": (naive / best_cost
                               if best_cost > 0 else float("inf")),
    }
    report["compiled"] = {
        "cost_to_dispatch": float(best_cost),
        "rounds_per_period": float(len(ordered)),
    }
    report["naive:linear"] = {
        "cost_to_dispatch": naive,
        "rounds_per_period": float(pod.size - 1),
    }
    report["naive:one_shot"] = {
        "cost_to_dispatch": one_shot,
        "rounds_per_period": 1.0,
    }
    stats["seconds"] = time.perf_counter() - t0
    if verbose:
        for name, sc in sorted(report.items()):
            print(f"[compile_all_to_all] {name}: cost_to_dispatch="
                  f"{sc['cost_to_dispatch']:.3f} "
                  f"({sc['rounds_per_period']:.0f} rounds)")
    return CompiledAllToAll(
        schedule=schedule, shifts_per_round=list(ordered), score=score,
        name=f"a2a:{best_name}", pod=pod, report=report,
        search={k: float(v) for k, v in stats.items()})


def menu_schedules(pod: PodSpec) -> Dict[str, List[DynamicTopology]]:
    """The FIXED menu the compiler competes against — the schedules a
    round-4 operator could hand-pick (``default_pod_schedule``'s
    candidates plus the rank-space classics)."""
    from bluefog_tpu.topology.dynamic import one_peer_dynamic_schedule
    from bluefog_tpu.topology.graphs import RingGraph

    menu = {
        "torus_exp2": torus_one_peer_schedule(pod.axes, "exp2"),
        "torus_single_hop": torus_one_peer_schedule(pod.axes,
                                                    "single_hop"),
    }
    if pod.size > 1:
        menu["logical_exp2"] = one_peer_dynamic_schedule(pod.size)
        menu["ring"] = one_peer_dynamic_schedule(
            RingGraph(pod.size, connect_style=1))
    return {k: v for k, v in menu.items() if v}


def compile_topology(pod: PodSpec, sketch: Optional[Sketch] = None,
                     eps: float = 1e-3,
                     verbose: bool = False,
                     hierarchical: bool = False):
    """Synthesize the mixing schedule for ``pod``: seed the sketch's
    shift families, weight-optimize each candidate (spectral-gap
    objective), hill-climb with Swing-style mutations, prune with the
    contraction bound (``cost_to_consensus >= sum of round costs`` —
    rounds-to-consensus is never below one period), and emit the
    winner as ``DynamicTopology`` rounds scored by the generic matrix
    machinery (the Fourier search score and the materialized-matrix
    score must agree; the tests assert it).

    ``hierarchical=True`` synthesizes the TWO-LEVEL decomposition
    instead: the inter-machine graph becomes a smaller
    ``machines x 1`` pod (calibrated DCN overrides carried over
    machine-wise) driven through the SAME sketch search, and the winner
    is rescored on the full pod by :func:`_hierarchical_score` — ICI
    reduce leg plus counterpart-expanded DCN leg per round, contraction
    from the machine matrix.  Returns a
    :class:`CompiledHierarchicalTopology` whose ``report`` keeps the
    machine-level search entries under ``machine:*`` and full-pod flat
    menu scores under ``menu:*`` for the apples-to-apples audit."""
    if hierarchical:
        if pod.machines < 2:
            raise ValueError(
                "hierarchical synthesis needs machines >= 2 — a "
                "single-machine pod has no DCN leg to decentralize")
        inner = compile_topology(_machine_pod(pod), sketch, eps=eps,
                                 verbose=verbose)
        score = _hierarchical_score(pod, inner.schedule, eps=eps)
        report = {f"machine:{k}": v for k, v in inner.report.items()}
        report["hierarchical"] = score
        for name, sched in menu_schedules(pod).items():
            report[f"menu:{name}"] = pod.score(sched, eps=eps)
        if verbose:
            print(f"[compile_topology] hierarchical "
                  f"(L={pod.chips_per_machine}, {inner.name}): "
                  f"cost_to_consensus={score['cost_to_consensus']:.3f}")
        return CompiledHierarchicalTopology(
            local_size=pod.chips_per_machine,
            machine_schedule=inner.schedule, score=score,
            name=f"hier:{inner.name}", pod=pod, report=report,
            search=inner.search)
    sketch = sketch or Sketch()
    t0 = time.perf_counter()
    axes = pod.axes
    # per-round metric cache, keyed by structure: (congestion, cost) —
    # one routing pass serves both the homogeneous and weighted figure
    metric_cache: Dict[Tuple, Tuple[float, float]] = {}
    stats = {"candidates": 0, "pruned": 0}

    def round_metrics(cand: Candidate) -> Tuple[List[float], List[float]]:
        congs, costs = [], []
        for rnd in cand.rounds:
            key = (cand.space, rnd.shifts)
            m = metric_cache.get(key)
            if m is None:
                pairs = _round_pairs(rnd, axes, cand.space)
                loads = link_loads(pairs, pod.torus)
                cong = max(loads.values(), default=0.0)
                cost = max((load * pod.link_cost(k)
                            for k, load in loads.items()), default=0.0)
                m = metric_cache[key] = (cong, cost)
            congs.append(m[0])
            costs.append(m[1])
        return congs, costs

    def evaluate(cand: Candidate, best_cost: float):
        """(candidate, score) after weight optimization, or None when
        the contraction bound prunes it.  Scores come from the shared
        ``_score_fields`` schema, same as PodSpec.score."""
        stats["candidates"] += 1
        congs, costs = round_metrics(cand)
        if best_cost < float("inf") and sum(costs) >= best_cost:
            stats["pruned"] += 1
            return None
        cand, sigma = _optimize_weights(cand, axes, sketch)
        return cand, _score_fields(congs, costs, sigma, eps)

    best: Optional[Tuple[Candidate, Dict[str, float]]] = None
    report: Dict[str, Dict[str, float]] = {}

    def consider(entry) -> bool:
        nonlocal best
        if entry is None:
            return False
        cand, score = entry
        if (best is None or score["cost_to_consensus"]
                < best[1]["cost_to_consensus"] - 1e-12):
            best = (cand, score)
            return True
        return False

    seeds = _seed_candidates(pod, sketch)
    if not seeds:
        raise ValueError(
            f"sketch {sketch.families!r} yields no candidate within "
            f"period {sketch.max_period} for pod {axes}")
    evaluated = []
    for seed in seeds:
        entry = evaluate(seed, float("inf"))
        if entry is None:
            continue
        evaluated.append(entry)
        report[f"seed:{seed.name}"] = entry[1]
        consider(entry)

    # hill-climb from every surviving seed (the search is cheap; the
    # per-candidate bound prunes hopeless mutants before weight opt)
    for cand, score in evaluated:
        current, current_score = cand, score
        for _ in range(sketch.mutation_rounds):
            best_mut = None
            for mut in _mutants(current, axes, sketch):
                entry = evaluate(
                    mut, current_score["cost_to_consensus"])
                if entry is None:
                    continue
                if (best_mut is None or entry[1]["cost_to_consensus"]
                        < best_mut[1]["cost_to_consensus"]):
                    best_mut = entry
            if best_mut is None or (best_mut[1]["cost_to_consensus"]
                                    >= current_score["cost_to_consensus"]
                                    - 1e-12):
                break
            current, current_score = best_mut
            consider(best_mut)
        if current is not cand:
            report[f"climbed:{cand.name}"] = current_score

    assert best is not None
    winner, _search_score = best
    schedule = materialize(winner, axes)
    # final score through the GENERIC machinery: materialized matrices,
    # measured contraction — the search's Fourier shortcut gets no say
    # in the published number (and must agree with it; tested).
    final = pod.score(schedule, eps=eps)
    report["compiled"] = final
    for name, sched in menu_schedules(pod).items():
        report[f"menu:{name}"] = pod.score(sched, eps=eps)
    stats["seconds"] = time.perf_counter() - t0
    if verbose:
        for name, sc in sorted(report.items()):
            print(f"[compile_topology] {name}: "
                  f"cost_to_consensus={sc['cost_to_consensus']:.3f} "
                  f"({sc['rounds_per_period']:.0f} rounds/period)")
    return CompiledTopology(schedule=schedule, score=final,
                            name=winner.name, pod=pod, report=report,
                            search={k: float(v)
                                    for k, v in stats.items()})


# ------------------------------------------------------------------ #
# CLI: compile offline, emit the schedule + score
# ------------------------------------------------------------------ #
def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m bluefog_tpu.topology.compiler --machines 4 --chips 8
    --emit json`` — offline synthesis for operators: prints the
    synthesized schedule plus its score dict (and the full
    per-candidate report), so a pod's schedule can be compiled and
    reviewed before a job ever runs."""
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m bluefog_tpu.topology.compiler",
        description="Synthesize a mixing schedule for a pod and emit "
                    "it as JSON or a summary table.")
    ap.add_argument("--machines", type=int, required=True)
    ap.add_argument("--chips", type=int, required=True,
                    help="chips per machine")
    ap.add_argument("--ici-cost", type=float, default=1.0)
    ap.add_argument("--dcn-cost", type=float, default=4.0)
    ap.add_argument("--eps", type=float, default=1e-3)
    ap.add_argument("--max-period", type=int, default=12)
    ap.add_argument("--max-degree", type=int, default=2)
    ap.add_argument("--traffic", default=None, metavar="SNAPSHOT.json",
                    help="per-edge traffic snapshot to calibrate link "
                         "costs from: JSON [[src, dst, bytes], ...] "
                         "(the bf_edge_bytes_total shape)")
    ap.add_argument("--contention", type=float, default=1.0,
                    help="calibration strength (see PodSpec.calibrated)")
    ap.add_argument("--hierarchical", action="store_true",
                    help="synthesize the two-level schedule: exact ICI "
                         "reduce per machine, compiled mixing across "
                         "DCN only")
    ap.add_argument("--emit", choices=("json", "summary"),
                    default="summary")
    args = ap.parse_args(argv)

    pod = PodSpec(args.machines, args.chips, ici_cost=args.ici_cost,
                  dcn_cost=args.dcn_cost)
    if args.traffic:
        with open(args.traffic) as fh:
            rows = json.load(fh)
        pod = pod.calibrated(
            {(int(s), int(d)): float(b) for s, d, b in rows},
            contention=args.contention)
    sketch = Sketch(max_period=args.max_period,
                    max_degree=args.max_degree)
    compiled = compile_topology(pod, sketch, eps=args.eps,
                                hierarchical=args.hierarchical)
    if args.emit == "json":
        print(json.dumps(compiled.as_json(), indent=1, sort_keys=True))
    else:
        print(f"winner: {compiled.name}  "
              f"(searched {compiled.search['candidates']:.0f} "
              f"candidates, pruned {compiled.search['pruned']:.0f}, "
              f"{compiled.search['seconds']:.2f}s)")
        for k, v in compiled.score.items():
            print(f"  {k}: {v:.6g}")
        for name, sc in sorted(compiled.report.items()):
            print(f"{name:>28}: cost_to_consensus="
                  f"{sc['cost_to_consensus']:.3f}  rounds/period="
                  f"{sc['rounds_per_period']:.0f}")
    sys.stdout.flush()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
