"""Closed-loop topology control plane: detect, re-plan, hot-swap.

``compile_topology`` (the TACCL-style sketch-guided synthesis) is a
one-shot planner: it prices links once and emits a schedule.  A fleet
is not one-shot — a DCN link congests, a rank turns into a persistent
straggler, an elastic shrink removes a quarter of the machines — and a
stale plan keeps mixing over exactly the links that telemetry says got
expensive.  This module closes the loop:

* **detect** — every ``window`` steps the plane reads windowed DELTAS of
  the per-edge timing counters (:class:`~bluefog_tpu.observe.fleet.
  TrafficDeltas` over ``bf_edge_seconds_total``; lifetime totals would
  drown a new hotspot in history), the
  :meth:`~bluefog_tpu.observe.fleet.StragglerDetector.z_scores`
  snapshot, and the live-set.  An edge is DEGRADED when its measured
  seconds-per-activation, normalized by its nominal link cost, exceeds
  the fleet-wide median by ``degrade_ratio`` — a relative test, so the
  units of the counters cancel and uniformly busy links never trip it.
  Degradation must persist ``patience`` consecutive windows before
  anything happens (debounce); a membership transition is structural
  and triggers immediately.

* **re-plan** — a trigger launches synthesis in a background thread
  (``synchronous=True`` runs it inline for deterministic tests):
  the pod is re-priced from the window's telemetry
  (:meth:`PodSpec.calibrated` over the seconds deltas, plus synthetic
  load on every edge incident to a flagged straggler), and candidates
  come from ``compile_topology`` (flat and, when the pod has >= 2
  machines, ``hierarchical=True`` flattened to rank rounds), the fixed
  menu, and structured live-machine rings.  Every candidate is
  *projected* onto the carrier and re-scored under the current dead
  mask; the winner is accepted only if its cost-to-consensus beats the
  re-scored incumbent by ``margin`` (hysteresis: a tie is noise, and
  swapping on noise flaps).

* **hot-swap** — the compiled train step's edge STRUCTURE is baked (the
  declared shift classes fix every table shape), so a candidate is
  deliverable only if each of its rounds' edges is a subset of the
  carrier round it lands on; projection re-expresses it over the
  carrier's declared edges with zero weight on the unused ones.  The
  swap is then pure weight DATA — ``(class_weights, self_weights)``
  pairs from :func:`~bluefog_tpu.resilience.healing.healed_comm_weights`
  over the projected specs, composed with the CURRENT dead mask — and
  costs zero recompiles.  A fresh swap is on probation: the plane
  tracks the params consensus distance and rolls back to the incumbent
  if it worsens past the pre-swap level; ``probation`` clean steps
  commit the candidate.  ``cooldown`` steps must pass between swaps.

All the hysteresis knobs default from ``BLUEFOG_TOPOLOGY_REPLAN_*``
(:mod:`bluefog_tpu.config`).  The one sanctioned place live weight
tables are produced for a running step is :func:`swap_comm_weights` —
the analysis lint's ``weight-swap-outside-boundary`` rule flags
in-place mutation of live weight operands anywhere else.

When the train step was built with error-feedback compressed mixing
(``compress="topk"``), the plane also owns the live compression ratio:
``mix_ratios`` is a strictly descending ladder whose first rung is the
BUILD ratio (the static ``k``; every other rung must be below it, since
the live ratio only masks a prefix of the baked wire slots).  The same
windowed degradation signal that triggers a re-plan first tries the
cheaper lever — step one rung DOWN the ladder (fewer wire bytes, pure
traced data, zero recompiles) — and only synthesizes a new topology
once the ladder is exhausted.  A ratio step is on probation exactly
like a topology swap (consensus health watched, rollback past
tolerance, commit after clean steps), and ``mix_recover_windows``
consecutive clean windows step back UP toward the build ratio, so a
transient congestion event does not permanently coarsen the mixing.
The one sanctioned producer of the live ratio is
:func:`swap_mix_ratio`, feeding ``train_step.set_mix_ratio`` at the
same step boundary ``swap_comm_weights`` delivers weight tables.
"""

from __future__ import annotations

import math
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

# like the rest of the topology package this module stays importable
# without jax: healing and observability are imported inside the
# functions that need them (they pull the jitted stack transitively)
from bluefog_tpu import config as _config
from bluefog_tpu.topology.compiler import CompiledAllToAll, PodSpec, Sketch, \
    compile_all_to_all, compile_topology, expand_machine_pairs, menu_schedules
from bluefog_tpu.topology.spec import DynamicTopology
from bluefog_tpu.topology.torus import rounds_from_contraction

__all__ = ["TopologyControlPlane", "swap_comm_weights", "swap_mix_ratio"]

# state machine (docs/topology.md draws it): STEADY watches windows,
# SYNTHESIZING has a re-plan in flight, CANDIDATE_READY holds an
# accepted plan awaiting its step boundary, PROBATION watches a fresh
# swap's health before committing it
STEADY = "steady"
SYNTHESIZING = "synthesizing"
CANDIDATE_READY = "candidate_ready"
PROBATION = "probation"


def swap_comm_weights(plane: "TopologyControlPlane", dead_mask) -> tuple:
    """The sanctioned step-boundary delivery: the ACTIVE (projected)
    schedule healed under the CURRENT dead mask, as traced-operand
    ``(class_weights, self_weights)`` pairs.  Swap and heal compose
    through this one helper — re-plan from the pristine spec, then
    re-apply the mask — and the lint's ``weight-swap-outside-boundary``
    rule holds every other code path to read-only use of live tables."""
    from bluefog_tpu.resilience.healing import healed_comm_weights

    return healed_comm_weights(plane.active_schedule(), dead_mask)


def swap_mix_ratio(plane: "TopologyControlPlane") -> float:
    """The sanctioned step-boundary delivery for the live compression
    ratio: the plane's active rung of the ``mix_ratios`` ladder, to be
    fed straight into ``train_step.set_mix_ratio`` after a
    ``mix_ratio_swap`` / ``mix_ratio_rollback`` event.  The ratio is
    pure traced data (the static top-k ``k`` was sized for the BUILD
    ratio — the ladder's first rung — and every lower rung only masks
    a prefix of those slots), so delivery costs zero recompiles."""
    return plane.mix_ratio()


def _consensus_distance(params, live: np.ndarray) -> float:
    """Max deviation of the LIVE ranks' rows from their mean, over every
    rank-major leaf — the health signal probation watches.  Leaves
    without a leading rank axis are ignored."""
    import jax

    n = live.shape[0]
    worst = 0.0
    for leaf in jax.tree.leaves(params):
        a = np.asarray(leaf, np.float64)
        if a.ndim < 1 or a.shape[0] != n:
            continue
        rows = a[live]
        if rows.size == 0:
            continue
        worst = max(worst, float(np.max(np.abs(rows - rows.mean(axis=0)))))
    return worst


def _local_ring_round(machines: int, local: int) -> Optional[DynamicTopology]:
    """One intra-machine mixing round: each chip averages with the next
    chip of its own machine's ICI ring (pure ICI, the cheap round the
    structured candidates interleave between DCN rounds)."""
    if local < 2:
        return None
    n = machines * local
    ew: Dict[Tuple[int, int], float] = {}
    for m in range(machines):
        for j in range(local):
            src = m * local + j
            dst = m * local + (j + 1) % local
            if src != dst:
                ew[(src, dst)] = 0.5
    return DynamicTopology.from_edges(n, ew, [0.5] * n)


def _machine_ring_round(pod: PodSpec, members: Sequence[int],
                        direction: int) -> Optional[DynamicTopology]:
    """One DCN mixing round: a directed ring over ``members`` (machine
    ids, cyclic in the given order and direction), expanded to the
    counterpart rank pairs the hierarchical exchange wires.  Ranks on
    machines outside ``members`` keep self weight 1.0 (they receive
    nothing — healing covers whether they are dead or merely skipped)."""
    k = len(members)
    if k < 2:
        return None
    order = list(members) if direction >= 0 else list(reversed(members))
    mpairs = [(order[i], order[(i + 1) % k]) for i in range(k)]
    pairs = expand_machine_pairs(mpairs, pod.chips_per_machine)
    sw = [1.0] * pod.size
    ew = {}
    for (s, d) in pairs:
        ew[(s, d)] = 0.5
        sw[d] = 0.5
    return DynamicTopology.from_edges(pod.size, ew, sw)


class TopologyControlPlane:
    """See the module docstring.  Drive it from a training loop by
    calling :meth:`on_step` once per completed step (``run_resilient``
    does this when given ``control=``); deliver weights through
    :func:`swap_comm_weights` / :meth:`healed_weights`.

    ``carrier`` is the schedule the train step was COMPILED over — the
    declared edge structure every candidate must project into.
    ``pod`` is the uncalibrated physical cost model; telemetry
    re-prices it per window.  ``registry``/``straggler`` are the
    telemetry sources (both optional; without them only membership
    transitions trigger).  ``candidates_fn(pod, dead_mask)`` overrides
    candidate generation (yields ``(name, schedule)`` pairs).
    ``health_fn(params, live_mask)`` overrides the probation health
    signal.  ``mix_ratios`` (strictly descending, first rung = the
    BUILD ratio) arms the compression-ratio ladder described in the
    module docstring; ``mix_recover_windows`` clean windows step the
    ratio back up toward the build rung."""

    def __init__(self, pod: PodSpec, carrier: Sequence[DynamicTopology], *,
                 sketch: Optional[Sketch] = None,
                 registry=None,
                 straggler=None,
                 contention: float = 3.0,
                 z_threshold: float = 3.0,
                 window: Optional[int] = None,
                 patience: Optional[int] = None,
                 degrade_ratio: Optional[float] = None,
                 margin: Optional[float] = None,
                 cooldown: Optional[int] = None,
                 probation: Optional[int] = None,
                 rollback_tolerance: float = 1.2,
                 eps: float = 1e-3,
                 synchronous: bool = False,
                 use_compiler: bool = True,
                 candidates_fn: Optional[Callable] = None,
                 health_fn: Optional[Callable] = None,
                 initial: Optional[Sequence[DynamicTopology]] = None,
                 mix_ratios: Optional[Sequence[float]] = None,
                 mix_recover_windows: int = 2,
                 blackbox=None):
        carrier = tuple(carrier)
        if not carrier:
            raise ValueError("control plane needs a non-empty carrier "
                             "schedule (the compiled step's rounds)")
        n = carrier[0].size
        if pod.size != n:
            raise ValueError(
                f"pod of size {pod.size} does not match the carrier "
                f"schedule's {n} ranks")
        self.pod = pod
        self.carrier = carrier
        self.sketch = sketch
        self._registry = registry
        self._straggler = straggler
        self._contention = float(contention)
        self._z_threshold = float(z_threshold)
        self.window = int(window if window is not None
                          else _config.topology_replan_window())
        self.patience = int(patience if patience is not None
                            else _config.topology_replan_patience())
        self.degrade_ratio = float(
            degrade_ratio if degrade_ratio is not None
            else _config.topology_replan_degrade_ratio())
        self.margin = float(margin if margin is not None
                            else _config.topology_replan_margin())
        self.cooldown = int(cooldown if cooldown is not None
                            else _config.topology_replan_cooldown())
        self.probation = int(probation if probation is not None
                             else _config.topology_replan_probation())
        self.rollback_tolerance = float(rollback_tolerance)
        self.eps = float(eps)
        self.synchronous = bool(synchronous)
        self.use_compiler = bool(use_compiler)
        self._candidates_fn = candidates_fn
        self._health_fn = health_fn or _consensus_distance
        if mix_ratios is not None:
            ladder = tuple(float(r) for r in mix_ratios)
            if len(ladder) < 2:
                raise ValueError(
                    "mix_ratios needs at least two rungs (the build "
                    "ratio plus one fallback) to be a ladder")
            if any(r <= 0.0 for r in ladder):
                raise ValueError("mix_ratios must all be positive")
            if any(b >= a for a, b in zip(ladder, ladder[1:])):
                raise ValueError(
                    "mix_ratios must be strictly descending — the "
                    "first rung is the BUILD ratio (it sized the "
                    "static k) and every later rung must fit inside "
                    "its wire slots")
            mix_ratios = ladder
        self.mix_ratios = mix_ratios
        self.mix_recover_windows = int(mix_recover_windows)

        from bluefog_tpu.observe.fleet import TrafficDeltas

        self._seconds = TrafficDeltas(registry, metric="bf_edge_seconds_total")
        self._bytes = TrafficDeltas(registry, metric="bf_edge_bytes_total")

        self._lock = threading.Lock()
        self._state = STEADY
        # ``initial`` is the plan actually RUNNING at startup (a carrier
        # usually declares a richer edge set than any one plan uses, so
        # alternatives stay expressible); it must project like any
        # candidate.  Default: the carrier's own weights.
        self._active: Tuple[DynamicTopology, ...] = (
            carrier if initial is None else self.project(initial))
        self._active_name = "carrier" if initial is None else "initial"
        self._previous: Optional[Tuple[DynamicTopology, ...]] = None
        self._previous_name = ""
        self._pending = None  # (name, projected specs, score, ready event)
        self._dead = np.zeros(n, bool)
        self._degraded_streak = 0
        self._membership_pending = False
        self._cooldown_until = 0
        self._probation_end = 0
        self._preswap_health: Optional[float] = None
        self._steps_seen = 0
        self._thread: Optional[threading.Thread] = None
        self._async_events: List[Tuple[str, dict]] = []
        # mix-ratio ladder position: index 0 = the build ratio.  A
        # pending probation mirrors the topology machine's fields but
        # stays independent of ``self._state`` (the topology machine
        # keeps STEADY while a ratio step is on probation).
        self._mix_index = 0
        self._mix_prev_index: Optional[int] = None
        self._mix_probation_end: Optional[int] = None
        self._mix_preswap_health: Optional[float] = None
        self._mix_clean_windows = 0
        # a2a (expert-dispatch) planning state: the last telemetry-
        # calibrated pod a trigger produced, and the a2a schedule
        # compiled against it.  Invalidated whenever a fresh
        # calibration lands, so plan_all_to_all() re-prices lazily.
        self._last_calibrated_pod: Optional[PodSpec] = None
        self._a2a_plan: Optional[CompiledAllToAll] = None
        self.swaps = 0
        self.rollbacks = 0
        self.triggers = 0
        self.mix_swaps = 0
        self.mix_rollbacks = 0
        self.a2a_replans = 0
        self.last_scores: Dict[str, float] = {}
        # decision flight recorder (observe.blackbox).  ``None``
        # records to the process-global ring gated by BLUEFOG_BLACKBOX;
        # an explicit BlackBox records unconditionally; ``False``
        # disables recording (the transparency-check "off" arm).
        # The ``*_event`` fields thread the causal chain: a trigger
        # parents its synthesis, an accepted candidate parents its
        # swap, a swap parents its probation verdict.
        self._blackbox = blackbox
        self._trigger_event = None
        self._swap_event = None
        self._mix_event = None

    # ------------------------------------------------------------ #
    # read-side surface
    # ------------------------------------------------------------ #
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def active_schedule(self) -> Tuple[DynamicTopology, ...]:
        """The schedule currently LIVE in the step — the incumbent, or
        a swapped-in candidate under probation.  Always carrier-shaped
        (same declared edges per round), so its healed weight tables
        fit the compiled step."""
        with self._lock:
            return self._active

    def active_name(self) -> str:
        with self._lock:
            return self._active_name

    def healed_weights(self, dead_mask) -> tuple:
        """:func:`swap_comm_weights` on the active schedule."""
        return swap_comm_weights(self, dead_mask)

    def mix_ratio(self) -> float:
        """The ACTIVE rung of the ``mix_ratios`` ladder (raises when
        the plane was built without one)."""
        if self.mix_ratios is None:
            raise ValueError(
                "this control plane has no mix_ratios ladder — pass "
                "mix_ratios=(build_ratio, ...) to let it drive the "
                "live compression ratio")
        with self._lock:
            return self.mix_ratios[self._mix_index]

    def plan_all_to_all(self, sketch: Optional[Sketch] = None,
                        ) -> CompiledAllToAll:
        """The expert-dispatch all-to-all schedule priced against the
        CURRENT network view: the last telemetry-calibrated pod when a
        trigger has re-priced one, the nominal pod before any window
        fired.  Lazy and cached — each fresh calibration invalidates
        the cache, so the first call after a trigger re-plans (counted
        in ``a2a_replans``) and later calls are free.  The emitted
        rounds feed ``moe.dispatch_plan`` exactly like a cold compile;
        whether a re-planned wire is worth a recompile is the caller's
        trade, the plane only prices it."""
        with self._lock:
            cached = self._a2a_plan
            pod = self._last_calibrated_pod or self.pod
        if cached is not None:
            return cached
        plan = compile_all_to_all(pod, sketch or self.sketch)
        with self._lock:
            self._a2a_plan = plan
            self.a2a_replans += 1
            self._decide(
                "a2a", "replan", step=self._steps_seen,
                parent=self._trigger_event,
                winner=getattr(plan, "name", None),
                calibrated=pod is not self.pod,
                replans=self.a2a_replans)
        return plan

    # ------------------------------------------------------------ #
    # projection: candidate -> carrier-shaped specs
    # ------------------------------------------------------------ #
    def project(self, schedule: Sequence[DynamicTopology],
                ) -> Tuple[DynamicTopology, ...]:
        """Re-express ``schedule`` over the carrier's declared edges:
        carrier round ``t`` plays candidate round ``t % len(schedule)``
        with the candidate's weights on its own edges and zero on every
        other declared edge.  The declared edge tuples (hence the shift
        classes, hence every table shape the compiled step baked) are
        untouched — that is what makes the swap recompile-free.  Raises
        ``ValueError`` when a candidate edge is not declared by the
        carrier round it lands on (the candidate is unexpressible and
        must be rejected, not silently dropped)."""
        schedule = list(schedule)
        if not schedule:
            raise ValueError("cannot project an empty schedule")
        n = self.carrier[0].size
        out = []
        for t, base in enumerate(self.carrier):
            cand = schedule[t % len(schedule)]
            if cand.size != n:
                raise ValueError(
                    f"candidate round over {cand.size} ranks cannot be "
                    f"projected onto a {n}-rank carrier")
            declared = set(base.edges)
            w = dict(zip(cand.edges, cand.edge_weight_values))
            missing = sorted(e for e, v in w.items()
                             if v != 0.0 and e not in declared)
            if missing:
                raise ValueError(
                    f"candidate round {t % len(schedule)} uses edges "
                    f"{missing[:4]} the carrier round {t} never "
                    f"declared — unexpressible without a recompile")
            vals = tuple(float(w.get(e, 0.0)) for e in base.edges)
            out.append(DynamicTopology(
                n, base.edges, vals,
                tuple(float(x) for x in cand.self_weight_values)))
        return tuple(out)

    # ------------------------------------------------------------ #
    # scoring: what actually plays, under the actual dead mask
    # ------------------------------------------------------------ #
    def score_active(self, specs: Sequence[DynamicTopology], dead_mask,
                     pod: Optional[PodSpec] = None) -> Dict[str, float]:
        """Cost-to-consensus of a carrier-shaped schedule AS DELIVERED:
        each round healed under ``dead_mask``, per-round cost = the pod
        cost of its remaining nonzero-weight edges (zero-weight edges
        push nothing), contraction measured on the live sub-matrix.
        The incumbent and every candidate are compared through this one
        function, so the margin gate is apples-to-apples."""
        from bluefog_tpu.resilience.healing import heal_spec, mixing_matrix

        pod = pod or self.pod
        dead = np.asarray(dead_mask, bool).reshape(-1)
        live = ~dead
        k = int(live.sum())
        if k == 0:
            raise ValueError("no live ranks to score")
        healed = [heal_spec(s, dead) for s in specs]
        costs = []
        for h in healed:
            pairs = [e for e, v in zip(h.edges, h.edge_weight_values)
                     if v != 0.0]
            costs.append(pod.round_cost(pairs))
        if k == 1:
            sigma = 0.0
        else:
            P = np.eye(k)
            for h in healed:
                M = mixing_matrix(h)[np.ix_(live, live)]
                P = M @ P
            dev = P - np.full((k, k), 1.0 / k)
            sigma = float(np.max(np.abs(np.linalg.eigvals(dev))))
        r2c = rounds_from_contraction(sigma, len(healed), self.eps)
        mean_cost = float(np.mean(costs)) if costs else 0.0
        return {
            "mean_round_cost": mean_cost,
            "max_round_cost": float(np.max(costs)) if costs else 0.0,
            "sigma": sigma,
            "rounds_to_consensus": r2c,
            "cost_to_consensus": mean_cost * r2c,
        }

    # ------------------------------------------------------------ #
    # candidate generation
    # ------------------------------------------------------------ #
    def _default_candidates(self, pod: PodSpec, dead: np.ndarray):
        """(name, schedule) candidates: synthesized (flat + flattened
        hierarchical), the fixed menu, and structured live-machine
        rings with 1 or 2 ICI rounds per DCN round (a smaller live
        fleet needs less DCN mixing per unit contraction, and the
        scorer — not this generator — decides whether that pays)."""
        out: List[Tuple[str, List[DynamicTopology]]] = []
        L = pod.chips_per_machine
        dead_m = dead.reshape(pod.machines, L).all(axis=1)
        live_machines = [m for m in range(pod.machines) if not dead_m[m]]
        ici = _local_ring_round(pod.machines, L)
        for direction in (+1, -1):
            ring = _machine_ring_round(pod, live_machines, direction)
            if ring is None:
                continue
            tag = "+1" if direction > 0 else "-1"
            if ici is not None:
                out.append((f"ring:{tag}:ici1", [ici, ring]))
                out.append((f"ring:{tag}:ici2", [ici, ici, ring]))
            else:
                out.append((f"ring:{tag}", [ring]))
        for name, sched in menu_schedules(pod).items():
            out.append((f"menu:{name}", list(sched)))
        if self.use_compiler:
            try:
                flat = compile_topology(pod, self.sketch, eps=self.eps)
                out.append((f"synth:{flat.name}", list(flat.schedule)))
            except ValueError:
                pass
            if pod.machines >= 2:
                try:
                    hier = compile_topology(pod, self.sketch, eps=self.eps,
                                            hierarchical=True)
                    rounds: List[DynamicTopology] = []
                    for mr in hier.machine_schedule:
                        if ici is not None:
                            rounds.append(ici)
                        pairs = expand_machine_pairs(list(mr.edges), L)
                        mw = dict(zip(mr.edges, mr.edge_weight_values))
                        ew = {}
                        sw = [0.0] * pod.size
                        for m in range(pod.machines):
                            for j in range(L):
                                sw[m * L + j] = float(
                                    mr.self_weight_values[m])
                        for (ms, md) in mr.edges:
                            for j in range(L):
                                ew[(ms * L + j, md * L + j)] = float(
                                    mw[(ms, md)])
                        rounds.append(DynamicTopology.from_edges(
                            pod.size, ew, sw))
                    if rounds:
                        out.append((f"synth:{hier.name}", rounds))
                except ValueError:
                    pass
        return out

    # ------------------------------------------------------------ #
    # telemetry window
    # ------------------------------------------------------------ #
    def _edge_activations(self) -> Dict[Tuple[int, int], int]:
        counts: Dict[Tuple[int, int], int] = {}
        for spec in self._active:
            for e, v in zip(spec.edges, spec.edge_weight_values):
                if v != 0.0:
                    counts[e] = counts.get(e, 0) + 1
        return counts

    def _window_degraded(self, secs: Dict[tuple, float],
                         z: Dict[int, float]) -> Tuple[bool, float]:
        """(degraded, worst_pressure): pressure of an edge = measured
        seconds per activation / nominal link cost, divided by the
        fleet-wide median of the same quantity.  Relative, so counter
        units cancel; > ``degrade_ratio`` marks the window degraded.
        A straggler z at/over threshold degrades the window too."""
        norms = {}
        counts = self._edge_activations()
        for e, s in secs.items():
            c = counts.get(e)
            if not c or s <= 0.0:
                continue
            nominal = self.pod.round_cost([e])
            if nominal <= 0.0:
                continue
            norms[e] = (s / c) / nominal
        worst = 0.0
        if len(norms) >= 2:
            med = float(np.median(list(norms.values())))
            if med > 0.0:
                worst = max(v / med for v in norms.values())
        z_hot = max(z.values(), default=0.0) >= self._z_threshold
        return (worst >= self.degrade_ratio or z_hot), worst

    def _calibration_traffic(self, secs: Dict[tuple, float],
                             z: Dict[int, float],
                             ) -> Dict[Tuple[int, int], float]:
        """The per-edge traffic the window's re-pricing feeds into
        :meth:`PodSpec.calibrated`: seconds deltas, plus synthetic load
        on every active edge incident to a flagged straggler (slow
        rank => expensive links => synthesis routes around it).  Pure
        given ``(secs, z)`` and the current active edge set — the
        decision recorder snapshots this dict so replay never has to
        reconstruct the activation state."""
        n = self.pod.size
        traffic = {k: float(v) for k, v in secs.items()
                   if 0 <= k[0] < n and 0 <= k[1] < n}
        hot = [r for r, v in z.items() if v >= self._z_threshold]
        if hot:
            base = max(traffic.values(), default=1.0)
            for e in self._edge_activations():
                for r in hot:
                    if r in e:
                        traffic[e] = (traffic.get(e, 0.0)
                                      + base * z[r] / self._z_threshold)
        return traffic

    def _pod_from_traffic(self, traffic: Dict[Tuple[int, int], float],
                          ) -> PodSpec:
        if not traffic:
            return self.pod
        return self.pod.calibrated(traffic, contention=self._contention)

    def _calibrated_pod(self, secs: Dict[tuple, float],
                        z: Dict[int, float]) -> PodSpec:
        """The window's re-priced pod (see
        :meth:`_calibration_traffic`)."""
        return self._pod_from_traffic(self._calibration_traffic(secs, z))

    # ------------------------------------------------------------ #
    # decision flight recorder
    # ------------------------------------------------------------ #
    def _decide(self, plane: str, kind: str, *, step: int, parent=None,
                telemetry=None, candidates=None, winner=None,
                winner_cost=None, margin=None, **detail):
        """The one blackbox emission seam of this plane (the
        ``decision-outside-recorder`` lint rule holds every transition
        to it).  Returns the recorded event or ``None`` when the
        recorder is off — callers thread ``None`` parents through."""
        from bluefog_tpu.observe import blackbox as _blackbox

        return _blackbox.record_decision(
            plane, kind, step=step, parent=parent, telemetry=telemetry,
            candidates=candidates, winner=winner,
            winner_cost=winner_cost, margin=margin,
            blackbox=self._blackbox, detail=detail or None)

    def _telemetry_snapshot(self, reason: str,
                            secs: Dict[tuple, float],
                            z: Dict[int, float],
                            dead: np.ndarray,
                            traffic: Dict[Tuple[int, int], float],
                            ) -> dict:
        """The canonical (digestable, replayable) record of everything
        a trigger saw: windowed edge-seconds deltas, straggler
        z-scores, the dead set, the derived calibration traffic, and
        the incumbent's name.  Keys are strings so the snapshot
        round-trips through JSONL dumps unchanged."""
        return {
            "reason": str(reason),
            "incumbent": self._active_name,
            "secs": {f"{a}-{b}": float(v)
                     for (a, b), v in sorted(secs.items())},
            "z": {str(r): float(v) for r, v in sorted(z.items())},
            "dead": [int(i) for i in np.flatnonzero(dead)],
            "traffic": {f"{a}-{b}": float(v)
                        for (a, b), v in sorted(traffic.items())},
        }

    def replay_decision(self, event, schedules) -> Dict[str, object]:
        """Re-derive a recorded ``synthesize`` decision from its OWN
        telemetry snapshot: rebuild the calibrated pod from the
        recorded traffic, re-project and re-score every recorded
        candidate (``schedules`` maps candidate/incumbent names back to
        their schedules), and return the winner/cost/margin that fall
        out.  The fleet-sim replay-verification pass machine-checks
        these against the event's recorded fields — "the fleet's
        decisions are reproducible from its own audit log"."""
        tele = event.telemetry
        traffic = {tuple(int(x) for x in k.split("-")): float(v)
                   for k, v in tele.get("traffic", {}).items()}
        pod = self._pod_from_traffic(traffic)
        dead = np.zeros(self.pod.size, bool)
        for i in tele.get("dead", ()):
            dead[int(i)] = True
        inc_name = tele.get("incumbent")
        costs: Dict[str, float] = {}
        for key in (event.candidates or {}):
            name = inc_name if key == "incumbent" else key
            proj = self.project(schedules[name])
            costs[key] = self.score_active(
                proj, dead, pod)["cost_to_consensus"]
        ranked = [k for k in costs if k != "incumbent"]
        if not ranked:
            return {"winner": None, "winner_cost": None,
                    "margin": None, "costs": costs}
        best = ranked[0]
        for k in ranked[1:]:
            if costs[k] < costs[best]:
                best = k
        inc = costs.get("incumbent")
        margin = (1.0 - costs[best] / inc
                  if inc is not None and inc > 0.0 else None)
        return {"winner": best, "winner_cost": costs[best],
                "margin": margin, "costs": costs}

    def replay_mix_decision(self, event) -> Dict[str, object]:
        """Re-derive a recorded mix-ladder move from its telemetry:
        the ladder is a fixed menu, so the "scoring" is the rung
        arithmetic — ``degraded`` steps down (coarser, fewer wire
        bytes), anything else steps up toward the build ratio."""
        tele = event.telemetry
        ladder = [float(r) for r in tele.get("ladder", ())]
        frm = int(tele["from_index"])
        to = frm + 1 if tele.get("reason") == "degraded" else frm - 1
        if not 0 <= to < len(ladder):
            return {"winner": None, "winner_cost": None, "to_index": to}
        return {"winner": format(ladder[to], ".9g"),
                "winner_cost": ladder[to], "to_index": to}

    # ------------------------------------------------------------ #
    # synthesis (background or inline)
    # ------------------------------------------------------------ #
    def _synthesize(self, pod: PodSpec, dead: np.ndarray,
                    step: Optional[int] = None,
                    telemetry: Optional[dict] = None,
                    trigger_ev=None) -> None:
        gen = self._candidates_fn or self._default_candidates
        incumbent = self.score_active(self._active, dead, pod)
        inc_cost = incumbent["cost_to_consensus"]
        scored: Dict[str, float] = {"incumbent": inc_cost}
        best = None
        for name, sched in gen(pod, dead):
            try:
                proj = self.project(sched)
            except ValueError:
                continue
            sc = self.score_active(proj, dead, pod)
            if not math.isfinite(sc["cost_to_consensus"]):
                continue
            scored[name] = sc["cost_to_consensus"]
            if best is None or (sc["cost_to_consensus"]
                                < best[2]["cost_to_consensus"]):
                best = (name, proj, sc)
        achieved = (1.0 - best[2]["cost_to_consensus"] / inc_cost
                    if best is not None and inc_cost > 0.0 else None)
        step = self._steps_seen if step is None else step
        with self._lock:
            self.last_scores = {
                "incumbent": inc_cost,
                "candidate": (best[2]["cost_to_consensus"]
                              if best else float("inf")),
            }
            synth_ev = self._decide(
                "topology", "synthesize", step=step, parent=trigger_ev,
                telemetry=telemetry, candidates=scored,
                winner=best[0] if best else None,
                winner_cost=best[2]["cost_to_consensus"] if best else None,
                margin=achieved)
            bar = inc_cost * (1.0 - self.margin)
            if best is not None and best[2]["cost_to_consensus"] < bar:
                ready_ev = self._decide(
                    "topology", "candidate_ready", step=step,
                    parent=synth_ev, winner=best[0],
                    winner_cost=best[2]["cost_to_consensus"])
                self._pending = (best[0], best[1], best[2], ready_ev)
                self._state = CANDIDATE_READY
            else:
                self._decide(
                    "topology", "reject", step=step, parent=synth_ev,
                    winner=best[0] if best else None,
                    winner_cost=(best[2]["cost_to_consensus"]
                                 if best else None),
                    margin=achieved, bar=bar)
                self._async_events.append(("topology_reject", {
                    "reason": "margin",
                    "incumbent": inc_cost,
                    "best": (best[2]["cost_to_consensus"]
                             if best else None),
                    "candidate": best[0] if best else None,
                }))
                self._state = STEADY
                self._degraded_streak = 0
                self._cooldown_until = self._steps_seen + self.cooldown

    def force_candidate(self, schedule: Sequence[DynamicTopology],
                        name: str = "forced") -> None:
        """Queue ``schedule`` for the next step boundary, bypassing the
        margin gate (projection is still enforced — an unexpressible
        plan raises).  The chaos bench uses this to inject a known-bad
        candidate and machine-check that probation rolls it back.
        The forced path records the same trigger→synthesize→
        candidate_ready chain a telemetry trigger would, so the audit
        trail of the injected swap reads like any other."""
        proj = self.project(schedule)
        with self._lock:
            step = self._steps_seen
            incumbent = self.score_active(self._active, self._dead)
            sc = self.score_active(proj, self._dead)
            inc_cost = incumbent["cost_to_consensus"]
            achieved = (1.0 - sc["cost_to_consensus"] / inc_cost
                        if inc_cost > 0.0 else None)
            self.last_scores = {
                "incumbent": inc_cost,
                "candidate": sc["cost_to_consensus"],
            }
            tele = self._telemetry_snapshot(
                "forced", {}, {}, self._dead, {})
            trig_ev = self._decide(
                "topology", "trigger", step=step, telemetry=tele)
            synth_ev = self._decide(
                "topology", "synthesize", step=step, parent=trig_ev,
                telemetry=tele,
                candidates={"incumbent": inc_cost,
                            name: sc["cost_to_consensus"]},
                winner=name, winner_cost=sc["cost_to_consensus"],
                margin=achieved)
            ready_ev = self._decide(
                "topology", "candidate_ready", step=step,
                parent=synth_ev, winner=name,
                winner_cost=sc["cost_to_consensus"])
            self._trigger_event = trig_ev
            self._pending = (name, proj, sc, ready_ev)
            self._state = CANDIDATE_READY

    # ------------------------------------------------------------ #
    # the per-step boundary hook
    # ------------------------------------------------------------ #
    def on_step(self, step: int, *, dead_mask=None,
                params=None) -> List[Tuple[str, dict]]:
        """Advance the control loop at a step boundary.  Returns
        ``(kind, detail)`` events — ``topology_trigger`` /
        ``topology_reject`` / ``topology_swap`` / ``topology_commit`` /
        ``topology_rollback``.  After a ``topology_swap`` or
        ``topology_rollback`` the caller must re-deliver weights
        (:func:`swap_comm_weights`); ``run_resilient`` does both."""
        events: List[Tuple[str, dict]] = []
        n = self.pod.size
        dead = (np.zeros(n, bool) if dead_mask is None
                else np.asarray(dead_mask, bool).reshape(-1))
        with self._lock:
            self._steps_seen = step
            events.extend(self._async_events)
            self._async_events = []
            if not np.array_equal(dead, self._dead):
                self._dead = dead.copy()
                self._membership_pending = True
            state = self._state
            # probation verdict first: a bad swap must not linger
            if state == PROBATION:
                if params is not None:
                    health = self._health_fn(params, ~dead)
                    if self._preswap_health is None:
                        self._preswap_health = health
                    elif health > (self._preswap_health
                                   * self.rollback_tolerance) + 1e-12:
                        self._active = self._previous
                        self._active_name = self._previous_name
                        self._previous = None
                        self._state = STEADY
                        self._degraded_streak = 0
                        self._cooldown_until = step + self.cooldown
                        self.rollbacks += 1
                        self._count("rollback")
                        self._decide(
                            "topology", "rollback", step=step,
                            parent=self._swap_event,
                            winner=self._active_name,
                            health=health,
                            preswap_health=self._preswap_health)
                        self._swap_event = None
                        events.append(("topology_rollback", {
                            "restored": self._active_name,
                            "health": health,
                            "preswap_health": self._preswap_health,
                        }))
                        return events
                if step >= self._probation_end:
                    self._previous = None
                    self._state = STEADY
                    self._degraded_streak = 0
                    self._cooldown_until = step + self.cooldown
                    self._count("commit")
                    self._decide(
                        "topology", "commit", step=step,
                        parent=self._swap_event,
                        winner=self._active_name)
                    self._swap_event = None
                    events.append(("topology_commit",
                                   {"schedule": self._active_name}))
                return events
            # mix-ratio probation verdict: mirrors the topology
            # machine's, but independently of ``self._state`` (which
            # stays STEADY while a ratio step is on probation)
            if self._mix_probation_end is not None:
                if params is not None:
                    health = self._health_fn(params, ~dead)
                    if self._mix_preswap_health is None:
                        self._mix_preswap_health = health
                    elif health > (self._mix_preswap_health
                                   * self.rollback_tolerance) + 1e-12:
                        restored = self._mix_prev_index
                        bad = self._mix_index
                        preswap = self._mix_preswap_health
                        self._mix_index = restored
                        self._mix_prev_index = None
                        self._mix_probation_end = None
                        self._mix_preswap_health = None
                        self._cooldown_until = step + self.cooldown
                        self.mix_rollbacks += 1
                        self._count("mix_rollback")
                        self._decide(
                            "mix", "rollback", step=step,
                            parent=self._mix_event,
                            winner=format(self.mix_ratios[restored],
                                          ".9g"),
                            health=health, preswap_health=preswap)
                        self._mix_event = None
                        events.append(("mix_ratio_rollback", {
                            "restored": self.mix_ratios[restored],
                            "ratio": self.mix_ratios[bad],
                            "health": health,
                            "preswap_health": preswap,
                        }))
                        return events
                if step >= self._mix_probation_end:
                    self._mix_prev_index = None
                    self._mix_probation_end = None
                    self._mix_preswap_health = None
                    self._cooldown_until = step + self.cooldown
                    self._count("mix_commit")
                    self._decide(
                        "mix", "commit", step=step,
                        parent=self._mix_event,
                        winner=format(self.mix_ratios[self._mix_index],
                                      ".9g"))
                    self._mix_event = None
                    events.append(("mix_ratio_commit", {
                        "ratio": self.mix_ratios[self._mix_index]}))
                return events
            if state == CANDIDATE_READY and self._pending is not None:
                name, proj, sc, ready_ev = self._pending
                self._pending = None
                self._previous = self._active
                self._previous_name = self._active_name
                self._active = proj
                self._active_name = name
                self._preswap_health = (
                    self._health_fn(params, ~dead)
                    if params is not None else None)
                self._state = PROBATION
                self._probation_end = step + self.probation
                self.swaps += 1
                self._count("swap")
                self._swap_event = self._decide(
                    "topology", "swap", step=step, parent=ready_ev,
                    winner=name,
                    winner_cost=sc["cost_to_consensus"])
                events.append(("topology_swap", {
                    "schedule": name,
                    "cost_to_consensus": sc["cost_to_consensus"],
                    "incumbent": self.last_scores.get("incumbent"),
                }))
                return events
            if state != STEADY:
                return events
            # STEADY: window bookkeeping + trigger decision
            if step < self._cooldown_until:
                return events
            membership = self._membership_pending
            window_due = (self.window > 0 and step > 0
                          and step % self.window == 0)
            if not membership and not window_due:
                return events
            secs = self._seconds.take() if window_due else {}
            self._bytes.take()  # keep the byte marker fresh too
            z = (self._straggler.z_scores()
                 if self._straggler is not None else {})
            reason = None
            if membership:
                reason = "membership"
                self._membership_pending = False
            elif window_due:
                degraded, worst = self._window_degraded(secs, z)
                if degraded:
                    self._degraded_streak += 1
                    self._mix_clean_windows = 0
                else:
                    self._degraded_streak = 0
                    self._mix_clean_windows += 1
                    # recovery: clean windows step the ratio back UP
                    # toward the build rung (compression costs mixing
                    # fidelity, so run the finest ratio the network
                    # affords); the step is on probation like any other
                    if (self.mix_ratios is not None
                            and self._mix_index > 0
                            and self._mix_clean_windows
                            >= self.mix_recover_windows):
                        self._mix_ladder_step(
                            step, self._mix_index - 1, "recover",
                            dead, params, events)
                        return events
                if self._degraded_streak >= self.patience:
                    # the cheap lever first: a rung DOWN the ladder is
                    # pure traced data; synthesis only once exhausted
                    if (self.mix_ratios is not None
                            and self._mix_index
                            < len(self.mix_ratios) - 1):
                        self._mix_ladder_step(
                            step, self._mix_index + 1, "degraded",
                            dead, params, events)
                        return events
                    reason = "degraded"
            if reason is None:
                return events
            self._degraded_streak = 0
            self._state = SYNTHESIZING
            traffic = self._calibration_traffic(secs, z)
            pod_w = self._pod_from_traffic(traffic)
            # the a2a planner prices against the same window's costs;
            # stale any cached dispatch schedule so it re-plans lazily
            self._last_calibrated_pod = pod_w
            self._a2a_plan = None
            dead_now = self._dead.copy()
            self.triggers += 1
            self._count("trigger")
            tele = self._telemetry_snapshot(
                reason, secs, z, dead_now, traffic)
            trig_ev = self._decide(
                "topology", "trigger", step=step, telemetry=tele)
            self._trigger_event = trig_ev
            events.append(("topology_trigger", {"reason": reason}))
        if self.synchronous:
            self._synthesize(pod_w, dead_now, step, tele, trig_ev)
        else:
            self._thread = threading.Thread(
                target=self._synthesize,
                args=(pod_w, dead_now, step, tele, trig_ev),
                name="bf-topology-replan", daemon=True)
            self._thread.start()
        return events

    def _mix_ladder_step(self, step: int, to_index: int, reason: str,
                         dead: np.ndarray, params, events) -> None:
        """Move the ladder to ``to_index`` and open probation on the
        step (caller holds the lock).  The new rung is live the moment
        the caller delivers it through :func:`swap_mix_ratio`."""
        prev = self._mix_index
        self._mix_prev_index = prev
        self._mix_index = to_index
        self._mix_probation_end = step + self.probation
        self._mix_preswap_health = (
            self._health_fn(params, ~dead)
            if params is not None else None)
        self._mix_clean_windows = 0
        self._degraded_streak = 0
        self.mix_swaps += 1
        self._count("mix_swap")
        self._mix_event = self._decide(
            "mix", "swap", step=step,
            telemetry={"reason": reason, "from_index": prev,
                       "to_index": to_index,
                       "ladder": [float(r) for r in self.mix_ratios]},
            candidates={format(r, ".9g"): float(r)
                        for r in self.mix_ratios},
            winner=format(self.mix_ratios[to_index], ".9g"),
            winner_cost=float(self.mix_ratios[to_index]))
        events.append(("mix_ratio_swap", {
            "ratio": self.mix_ratios[to_index],
            "previous": self.mix_ratios[prev],
            "reason": reason,
        }))

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for an in-flight background synthesis (tests)."""
        t = self._thread
        if t is not None:
            t.join(timeout)

    @staticmethod
    def _count(kind: str) -> None:
        from bluefog_tpu import observe

        if observe.enabled():
            observe.get_registry().counter(
                "bf_topology_replan_total",
                "topology control-plane transitions", kind=kind).inc()
