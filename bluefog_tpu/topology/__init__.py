"""Virtual topology library: static graph generators, weights, dynamic
schedules, and the device-ready ``Topology`` spec.

Reference parity: bluefog/common/topology_util.py (plus
bluefog/torch/topology_util.py helpers, re-exported from
``bluefog_tpu.topology.infer``).
"""

from bluefog_tpu.topology.graphs import (  # noqa: F401
    ExponentialTwoGraph,
    ExponentialGraph,
    SymmetricExponentialGraph,
    MeshGrid2DGraph,
    StarGraph,
    RingGraph,
    FullyConnectedGraph,
    IsTopologyEquivalent,
    IsRegularGraph,
    GetRecvWeights,
    GetSendWeights,
    circulant_graph,
)
from bluefog_tpu.topology.dynamic import (  # noqa: F401
    GetDynamicOnePeerSendRecvRanks,
    GetExp2DynamicSendRecvMachineRanks,
    GetInnerOuterRingDynamicSendRecvRanks,
    GetInnerOuterExpo2DynamicSendRecvRanks,
    one_peer_round,
    one_peer_dynamic_schedule,
    inner_outer_ring_round,
    inner_outer_expo2_round,
    exp2_machine_round,
)
from bluefog_tpu.topology.spec import (  # noqa: F401
    Topology,
    DynamicTopology,
    ShiftClass,
    uniform_topology_spec,
)
from bluefog_tpu.topology.infer import (  # noqa: F401
    InferSourceFromDestinationRanks,
    InferDestinationFromSourceRanks,
)
from bluefog_tpu.topology.torus import (  # noqa: F401
    TorusSpec,
    torus_one_peer_schedule,
    torus_shift_round,
    round_congestion,
    schedule_congestion,
    consensus_contraction,
    rounds_from_contraction,
    rounds_to_consensus,
    score_schedule,
    default_pod_schedule,
)
from bluefog_tpu.topology.compiler import (  # noqa: F401
    PodSpec,
    Sketch,
    CompiledTopology,
    CompiledHierarchicalTopology,
    compile_topology,
    expand_machine_pairs,
    menu_schedules,
)
from bluefog_tpu.topology.control import (  # noqa: F401
    TopologyControlPlane,
    swap_comm_weights,
)
