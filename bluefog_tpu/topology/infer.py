"""Infer reverse edge sets of a dynamic topology.

Reference parity: bluefog/torch/topology_util.py:22-108
(``InferSourceFromDestinationRanks`` / ``InferDestinationFromSourceRanks``).

The reference implements these as collective calls (two allgathers) because
each MPI rank only knows its own send/recv set.  Under SPMD every process
computes the full world mapping deterministically, so these are pure host
functions over the world view: pass ``ranks_per_rank`` as a list of lists
(entry r = that rank's dst/src list).  The optional ``rank`` argument selects
one rank's answer, matching the reference's per-rank return.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["InferSourceFromDestinationRanks", "InferDestinationFromSourceRanks"]


def _check_world(ranks_per_rank: Sequence[Sequence[int]]) -> None:
    size = len(ranks_per_rank)
    for self_rank, lst in enumerate(ranks_per_rank):
        for r in lst:
            if not isinstance(r, (int, np.integer)):
                raise AssertionError("contain element that is not integer.")
            if r < 0 or r >= size:
                raise AssertionError(
                    "contain element that is not between 0 and size-1."
                )
        if len(set(lst)) != len(lst):
            raise AssertionError("contain duplicated elements.")
        if self_rank in lst:
            raise AssertionError("contain self rank.")


def _invert(ranks_per_rank: Sequence[Sequence[int]]) -> List[List[int]]:
    size = len(ranks_per_rank)
    inverse: List[List[int]] = [[] for _ in range(size)]
    for src, lst in enumerate(ranks_per_rank):
        for dst in sorted(lst):
            inverse[dst].append(src)
    return inverse


def _adjacency(ranks_per_rank: Sequence[Sequence[int]], transpose: bool) -> np.ndarray:
    size = len(ranks_per_rank)
    w = np.eye(size)
    for k, adj in enumerate(ranks_per_rank):
        w[k, sorted(adj)] = 1
    if transpose:
        w = w.T
    # Reference normalization (torch/topology_util.py:108): divide entry
    # (i, j) by the sum of row j ("column normalized style").
    return w / w.sum(axis=1)


def InferSourceFromDestinationRanks(
    dst_ranks_per_rank: Sequence[Sequence[int]],
    construct_adjacency_matrix: bool = False,
    rank: Optional[int] = None,
) -> Union[List, Tuple[List, np.ndarray]]:
    """Given every rank's destination list, return every rank's source list
    (or ``rank``'s if given); optionally the weighted adjacency matrix."""
    _check_world(dst_ranks_per_rank)
    sources = _invert(dst_ranks_per_rank)
    result = sources if rank is None else sources[rank]
    if not construct_adjacency_matrix:
        return result
    return result, _adjacency(dst_ranks_per_rank, transpose=False)


def InferDestinationFromSourceRanks(
    src_ranks_per_rank: Sequence[Sequence[int]],
    construct_adjacency_matrix: bool = False,
    rank: Optional[int] = None,
) -> Union[List, Tuple[List, np.ndarray]]:
    """Given every rank's source list, return every rank's destination list
    (or ``rank``'s if given); optionally the weighted adjacency matrix."""
    _check_world(src_ranks_per_rank)
    dests = _invert(src_ranks_per_rank)
    result = dests if rank is None else dests[rank]
    if not construct_adjacency_matrix:
        return result
    return result, _adjacency(src_ranks_per_rank, transpose=True)
