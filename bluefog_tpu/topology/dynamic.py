"""Dynamic (time-varying) topology generators.

Behavioral parity with the reference's dynamic one-peer iterators
(reference: bluefog/common/topology_util.py:315-554).  Each generator yields
``(send_ranks, recv_ranks)`` per round for a given rank.

The TPU build adds world-level round functions (``*_round``): one call
returns the **full** send map for all ranks at a round, which is what the
collective controller needs to build a ``DynamicTopology`` (the per-rank
iterators are derived views of these).  Rounds are deterministic functions of
the round index, so every process/trace computes the same permutation without
any negotiation.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple

import networkx as nx
import numpy as np

__all__ = [
    "GetDynamicOnePeerSendRecvRanks",
    "GetExp2DynamicSendRecvMachineRanks",
    "GetInnerOuterRingDynamicSendRecvRanks",
    "GetInnerOuterExpo2DynamicSendRecvRanks",
    "one_peer_round",
    "one_peer_dynamic_schedule",
    "inner_outer_ring_round",
    "inner_outer_expo2_round",
    "exp2_machine_round",
]


def _clockwise_successors(topo: nx.DiGraph) -> List[List[int]]:
    """Per-rank out-neighbors (self excluded), ordered clockwise starting
    just after the rank itself (reference topology_util.py:335-343)."""
    size = topo.number_of_nodes()
    ordered = []
    for rank in range(size):
        succ = [s for s in topo.successors(rank) if s != rank]
        succ.sort(key=lambda s: (s - rank) % size)
        ordered.append(succ)
    return ordered


def one_peer_round(topo: nx.DiGraph, index: int) -> Dict[int, int]:
    """Send map {src: dst} for round ``index`` of the one-peer dynamic
    schedule over base graph ``topo``."""
    ordered = _clockwise_successors(topo)
    send = {}
    for rank, succ in enumerate(ordered):
        if succ:
            send[rank] = succ[index % len(succ)]
    return send


def one_peer_dynamic_schedule(topo, rounds: int = None) -> list:
    """The framework's headline dynamic mode, packaged: the full cycle of
    one-peer rounds as ``DynamicTopology`` specs with the reference's
    uniform combine weights 1/(in_degree+1) (reference
    torch/mpi_ops.py:504-510).  Feed the result to
    ``optim.functional.build_train_step(schedule=...)`` — the step index
    picks the round via ``lax.switch``.

    ``topo``: a DiGraph, or an int n for ExponentialTwoGraph(n) — BlueFog's
    O(1)-communication-per-step graph (reference README.rst:51-60).
    """
    from bluefog_tpu.topology.graphs import ExponentialTwoGraph
    from bluefog_tpu.topology.spec import DynamicTopology

    if isinstance(topo, int):
        topo = ExponentialTwoGraph(topo)
    n = topo.number_of_nodes()
    if rounds is None:
        rounds = max(1, max(
            len(s) for s in _clockwise_successors(topo)) if n > 1 else 1)
    schedule = []
    for i in range(rounds):
        send = one_peer_round(topo, i)
        recv: Dict[int, List[int]] = {r: [] for r in range(n)}
        for src, dst in send.items():
            recv[dst].append(src)
        edge_weights, selfs = {}, []
        for r in range(n):
            w = 1.0 / (len(recv[r]) + 1)
            selfs.append(w)
            for src in recv[r]:
                edge_weights[(src, r)] = w
        schedule.append(DynamicTopology.from_edges(n, edge_weights, selfs))
    return schedule


def GetDynamicOnePeerSendRecvRanks(
    topo: nx.DiGraph, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Yield ([send_rank], recv_ranks) per round: each rank cycles clockwise
    through its out-neighbors; recv set is the exact inverse.

    Parity: reference topology_util.py:315-357.
    """
    index = 0
    while True:
        send = one_peer_round(topo, index)
        recv_ranks = sorted(src for src, dst in send.items() if dst == self_rank)
        yield [send[self_rank]], recv_ranks
        index += 1


def exp2_machine_round(num_machines: int, machine_id: int, index: int) -> Tuple[int, int]:
    """(send_machine, recv_machine) for the exponential-2 machine schedule."""
    exp2_size = int(np.log2(num_machines - 1)) if num_machines > 1 else 0
    dist = 2 ** (index % (exp2_size + 1))
    return (machine_id + dist) % num_machines, (machine_id - dist) % num_machines


def GetExp2DynamicSendRecvMachineRanks(
    world_size: int, local_size: int, self_rank: int, local_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Yield ([send_machine], [recv_machine]) cycling over power-of-2 machine
    distances.  Homogeneous placement required.

    Parity: reference topology_util.py:360-397.
    """
    assert self_rank % local_size == local_rank, (
        "It should be used under homogeneous environment only."
    )
    assert world_size % local_size == 0, (
        "It should be used under homogeneous environment only."
    )
    assert world_size > local_size, "It should be used under at least two machines case."
    machine_id = self_rank // local_size
    num_machines = world_size // local_size
    index = 0
    while True:
        send_m, recv_m = exp2_machine_round(num_machines, machine_id, index)
        yield [send_m], [recv_m]
        index += 1


def _ring_peers(
    local_rank: int, outside_id: int, nodes_per_machine: int
) -> Tuple[int, int]:
    """Send/recv local ids for the inner ring that skips ``outside_id``."""
    send_local = (local_rank + 1) % nodes_per_machine
    if send_local == outside_id:
        send_local = (send_local + 1) % nodes_per_machine
    recv_local = (local_rank - 1) % nodes_per_machine
    if recv_local == outside_id:
        recv_local = (recv_local - 1) % nodes_per_machine
    return send_local, recv_local


def inner_outer_ring_round(
    world_size: int, local_size: int, self_rank: int, index: int
) -> Tuple[int, int]:
    """(send_rank, recv_rank) for the inner-ring/outer-ring schedule: one
    designated local rank per round talks ring-wise across machines, everyone
    else rings within the machine (skipping the outside-goer)."""
    num_machines = world_size // local_size
    machine_id, local_rank = divmod(self_rank, local_size)
    outside_id = index % local_size
    if outside_id == local_rank:
        send = ((machine_id + 1) % num_machines) * local_size + local_rank
        recv = ((machine_id - 1) % num_machines) * local_size + local_rank
    else:
        send_local, recv_local = _ring_peers(local_rank, outside_id, local_size)
        send = machine_id * local_size + send_local
        recv = machine_id * local_size + recv_local
    return send, recv


def GetInnerOuterRingDynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Parity: reference topology_util.py:399-463."""
    assert world_size % local_size == 0, (
        "It should be used under homogeneous environment only."
    )
    assert local_size > 2, (
        "Unsupported case: nodes_per_machine must exceed 2. Consider "
        "hierarchical_neighbor_allreduce or "
        "GetDynamicOnePeerSendRecvRanks instead."
    )
    index = 0
    while True:
        send, recv = inner_outer_ring_round(world_size, local_size, self_rank, index)
        yield [send], [recv]
        index += 1


def inner_outer_expo2_round(
    world_size: int, local_size: int, self_rank: int, index: int
) -> Tuple[int, int]:
    """(send_rank, recv_rank) for the inner-exp2/outer-exp2 schedule."""
    num_machines = world_size // local_size
    machine_id, local_rank = divmod(self_rank, local_size)
    outside_id = index % local_size
    exp2_out = int(np.log2(num_machines - 1))
    exp2_in = 0 if local_size == 2 else int(np.log2(local_size - 2))

    if outside_id == local_rank:
        dist = 2 ** (index % (exp2_out + 1))
        send = ((machine_id + dist) % num_machines) * local_size + local_rank
        recv = ((machine_id - dist) % num_machines) * local_size + local_rank
        return send, recv

    # Inner exp2 over the remaining local ranks, hopping over the outside-goer.
    dist = 2 ** (index % (exp2_in + 1))
    send_dist = dist + 1 if dist >= (outside_id - local_rank) % local_size else dist
    recv_dist = dist + 1 if dist >= (local_rank - outside_id) % local_size else dist
    send = machine_id * local_size + (local_rank + send_dist) % local_size
    recv = machine_id * local_size + (local_rank - recv_dist) % local_size
    return send, recv


def GetInnerOuterExpo2DynamicSendRecvRanks(
    world_size: int, local_size: int, self_rank: int
) -> Iterator[Tuple[List[int], List[int]]]:
    """Parity: reference topology_util.py:466-554."""
    assert world_size % local_size == 0, (
        "It should be used under homogeneous environment only."
    )
    assert local_size > 2, (
        "Unsupported case: nodes_per_machine must exceed 2. Consider "
        "hierarchical_neighbor_allreduce or "
        "GetDynamicOnePeerSendRecvRanks instead."
    )
    index = 0
    while True:
        send, recv = inner_outer_expo2_round(world_size, local_size, self_rank, index)
        yield [send], [recv]
        index += 1
