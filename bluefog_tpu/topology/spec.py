"""Device-ready topology specification.

The reference keeps its topology inside an MPI distributed-graph communicator
(reference: bluefog/common/mpi_context.cc:412-425) and re-reads the neighbor
lists per op.  On TPU the equivalent artifact is a **shift decomposition**: the
edge set {(src, dst)} of a digraph over n ranks is partitioned by
``s = (dst - src) mod n``; each class is a partial permutation of the mesh
axis, i.e. exactly one ``lax.ppermute``.  Circulant graphs (exponential-2,
ring, fully-connected) decompose into a handful of classes; the weighted
combine then reads per-rank weight vectors indexed by ``lax.axis_index``.

This module is pure NumPy (host-side, trace-time) — nothing here touches jax.
"""

from __future__ import annotations

# This module legitimately constructs weight tables from scratch — the
# analysis lint's weight-matrix-bypass rule treats it as an authority
# (everywhere else, tables must come from the shared helpers here).
_WEIGHT_AUTHORITY = True

import dataclasses
import functools
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import networkx as nx
import numpy as np

__all__ = ["ShiftClass", "Topology", "DynamicTopology",
           "self_weights_of", "uniform_topology_spec"]


def self_weights_of(spec) -> Tuple[float, ...]:
    """Per-rank self weights of either spec flavor (Topology keeps them
    as ``self_weights``, DynamicTopology as ``self_weight_values``) —
    the one accessor shared by the collectives and the resilience
    healing planner."""
    if isinstance(spec, Topology):
        return spec.self_weights
    return spec.self_weight_values


def uniform_topology_spec(graph: nx.DiGraph) -> "Topology":
    """Resolve a graph to the reference's *unweighted* combine: every rank
    uses 1/(in_degree+1) for itself and each in-neighbor
    (reference torch/mpi_ops.py:504-510)."""
    n = graph.number_of_nodes()
    adj = nx.to_numpy_array(graph) != 0.0
    np.fill_diagonal(adj, False)
    weights = np.zeros((n, n))
    for dst in range(n):
        srcs = np.nonzero(adj[:, dst])[0]
        w = 1.0 / (len(srcs) + 1)
        weights[srcs, dst] = w
        weights[dst, dst] = w
    return Topology.from_weight_matrix(weights)


@dataclasses.dataclass(frozen=True)
class ShiftClass:
    """One ppermute-able slice of an edge set.

    ``perm``: tuple of (src, dst) pairs, each src/dst appearing at most once.
    ``recv_weights``: length-n vector; entry d is the weight rank d applies to
    the value it receives through this class (0.0 if d receives nothing).
    """

    shift: int
    perm: Tuple[Tuple[int, int], ...]
    recv_weights: Tuple[float, ...]


def _decompose(
    size: int,
    edges: Sequence[Tuple[int, int]],
    edge_weights: Dict[Tuple[int, int], float],
) -> Tuple[ShiftClass, ...]:
    by_shift: Dict[int, List[Tuple[int, int]]] = {}
    for (src, dst) in edges:
        if src == dst:
            continue
        by_shift.setdefault((dst - src) % size, []).append((src, dst))
    classes = []
    for shift in sorted(by_shift):
        pairs = sorted(by_shift[shift])
        recv = [0.0] * size
        seen_src, seen_dst = set(), set()
        for src, dst in pairs:
            if src in seen_src or dst in seen_dst:
                raise ValueError(
                    f"shift class {shift} is not a partial permutation: {pairs}"
                )
            seen_src.add(src)
            seen_dst.add(dst)
            recv[dst] = float(edge_weights[(src, dst)])
        classes.append(ShiftClass(shift, tuple(pairs), tuple(recv)))
    return tuple(classes)


@dataclasses.dataclass(frozen=True)
class Topology:
    """A static weighted digraph, flattened to arrays + shift classes.

    ``weights[src, dst]`` is the combine weight dst applies to src's value
    (reference convention, bluefog/common/topology_util.py:40-51).
    """

    size: int
    weights_bytes: bytes  # float64 [n, n] raw buffer (hashable)
    shift_classes: Tuple[ShiftClass, ...]
    self_weights: Tuple[float, ...]

    @staticmethod
    def from_graph(graph: nx.DiGraph) -> "Topology":
        weights = nx.to_numpy_array(graph, dtype=np.float64)
        return Topology.from_weight_matrix(weights)

    @staticmethod
    def from_weight_matrix(weights: np.ndarray) -> "Topology":
        weights = np.asarray(weights, dtype=np.float64)
        n = weights.shape[0]
        assert weights.shape == (n, n)
        edges = [(int(s), int(d)) for s, d in zip(*np.nonzero(weights))]
        ew = {(s, d): float(weights[s, d]) for (s, d) in edges}
        classes = _decompose(n, edges, ew)
        self_w = tuple(float(weights[i, i]) for i in range(n))
        return Topology(
            size=n,
            weights_bytes=weights.tobytes(),
            shift_classes=classes,
            self_weights=self_w,
        )

    @property
    def weights(self) -> np.ndarray:
        return np.frombuffer(self.weights_bytes, dtype=np.float64).reshape(
            self.size, self.size
        )

    def digest(self) -> str:
        return hashlib.sha1(self.weights_bytes).hexdigest()[:16]

    def to_graph(self) -> nx.DiGraph:
        return nx.from_numpy_array(self.weights, create_using=nx.DiGraph)

    def in_neighbors(self, rank: int) -> List[int]:
        w = self.weights
        return [s for s in range(self.size) if s != rank and w[s, rank] != 0.0]

    def out_neighbors(self, rank: int) -> List[int]:
        w = self.weights
        return [d for d in range(self.size) if d != rank and w[rank, d] != 0.0]

    def in_degrees(self) -> np.ndarray:
        w = self.weights
        off = (w != 0.0) & ~np.eye(self.size, dtype=bool)
        return off.sum(axis=0)

    def out_degrees(self) -> np.ndarray:
        w = self.weights
        off = (w != 0.0) & ~np.eye(self.size, dtype=bool)
        return off.sum(axis=1)

    def max_in_degree(self) -> int:
        return int(self.in_degrees().max()) if self.size else 0

    def is_uniform_in_degree(self) -> bool:
        deg = self.in_degrees()
        return bool((deg == deg[0]).all())


@dataclasses.dataclass(frozen=True)
class DynamicTopology:
    """One round of a dynamic topology: explicit per-edge send/recv sets.

    Built from the per-rank (send_ranks, recv_ranks) the dynamic generators
    yield (reference: bluefog/common/topology_util.py:315-554) plus the
    weights the caller supplies (reference dynamic-mode ``dst_weights`` /
    ``src_weights``, bluefog/torch/mpi_ops.py:540-651).

    ``edge_weights[(src, dst)]`` is the total scale applied to src's value as
    seen by dst (sender-side dst_weight x receiver-side src_weight — under
    SPMD both collapse to one multiply at the receiver).
    """

    size: int
    edges: Tuple[Tuple[int, int], ...]
    edge_weight_values: Tuple[float, ...]
    self_weight_values: Tuple[float, ...]  # length n

    @staticmethod
    def from_edges(
        size: int,
        edge_weights: Dict[Tuple[int, int], float],
        self_weights: Optional[Sequence[float]] = None,
    ) -> "DynamicTopology":
        edges = tuple(sorted(edge_weights))
        vals = tuple(float(edge_weights[e]) for e in edges)
        if self_weights is None:
            self_weights = [0.0] * size
        return DynamicTopology(size, edges, vals, tuple(float(w) for w in self_weights))

    @functools.cached_property
    def shift_classes(self) -> Tuple[ShiftClass, ...]:
        # cached_property writes through __dict__, which frozen
        # dataclasses allow — the decomposition of an immutable edge set
        # never changes, so repeated access (eager hot path) is O(1)
        ew = dict(zip(self.edges, self.edge_weight_values))
        return _decompose(self.size, self.edges, ew)

    def in_degrees(self) -> np.ndarray:
        """Per-rank in-degree (received edges) of this round — the
        quantity the topology compiler's sketch bounds (``max_degree``):
        one-peer rounds are 1 everywhere, multi-shift rounds higher."""
        deg = np.zeros(self.size, np.int64)
        for (_, dst) in self.edges:
            deg[dst] += 1
        return deg

    def max_in_degree(self) -> int:
        return int(self.in_degrees().max()) if self.edges else 0

    def digest(self) -> str:
        h = hashlib.sha1(repr((self.size, self.edges, self.edge_weight_values,
                               self.self_weight_values)).encode())
        return h.hexdigest()[:16]
