"""Static virtual-topology generators.

Behavioral parity with the reference's graph library
(reference: bluefog/common/topology_util.py:66-313).  All generators return a
weighted ``networkx.DiGraph`` whose adjacency matrix W satisfies the
averaging convention used throughout the framework:

    new_x[rank] = W[rank, rank] * x[rank] + sum_{src} W[src, rank] * x[src]

i.e. **W[i, j] is the weight node j applies to the value it receives from
node i** (column-stochastic combination; reference GetRecvWeights,
topology_util.py:40-51).

Most graphs here are circulant: node ``i`` connects to ``(i + s) % n`` for a
fixed set of shifts ``s``.  Circulant topologies are exactly the ones that
lower to a single ``lax.ppermute`` per shift on TPU, so we build them from an
explicit shift->weight profile and keep that profile around (as graph
metadata) for the collective controller.
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Tuple

import networkx as nx
import numpy as np

__all__ = [
    "ExponentialTwoGraph",
    "ExponentialGraph",
    "SymmetricExponentialGraph",
    "MeshGrid2DGraph",
    "StarGraph",
    "RingGraph",
    "FullyConnectedGraph",
    "IsTopologyEquivalent",
    "IsRegularGraph",
    "GetRecvWeights",
    "GetSendWeights",
    "circulant_graph",
]


def _is_power_of(x: int, base: int) -> bool:
    if x <= 0:
        return False
    p = 1
    while p < x:
        p *= base
    return p == x


def circulant_graph(size: int, shift_weights: Dict[int, float]) -> nx.DiGraph:
    """Build a circulant digraph from a ``{shift: weight}`` profile.

    Edge (i, (i+s) % size) gets weight ``shift_weights[s]`` for every node i.
    Shift 0 is the self-loop weight.
    """
    row = np.zeros(size)
    for shift, w in shift_weights.items():
        row[shift % size] += w
    adjacency = np.stack([np.roll(row, i) for i in range(size)])
    graph = nx.from_numpy_array(adjacency, create_using=nx.DiGraph)
    return graph


def _uniform_circulant(size: int, shifts) -> nx.DiGraph:
    """Circulant graph with uniform weight 1/(len(shifts)) over given shifts
    (which include the self shift 0)."""
    shifts = sorted(set(int(s) % size for s in shifts))
    w = 1.0 / len(shifts)
    return circulant_graph(size, {s: w for s in shifts})


def ExponentialTwoGraph(size: int) -> nx.DiGraph:
    """Each node i connects to i + 2**k for all 2**k < size.

    Parity: reference topology_util.py:66-89 (selects indices whose value is
    a power of two via the ``i & (i-1) == 0`` trick, including index 1==2**0
    and index 0 as self).
    """
    assert size > 0
    shifts = [0] + [s for s in range(1, size) if s & (s - 1) == 0]
    return _uniform_circulant(size, shifts)


def ExponentialGraph(size: int, base: int = 2) -> nx.DiGraph:
    """Each node i connects to i + base**k (all powers of ``base`` < size).

    Parity: reference topology_util.py:99-125.
    """
    assert size > 0
    shifts = [0] + [s for s in range(1, size) if _is_power_of(s, base)]
    return _uniform_circulant(size, shifts)


def SymmetricExponentialGraph(size: int, base: int = 4) -> nx.DiGraph:
    """Exponential graph whose second half of shifts mirrors the first half.

    Parity: reference topology_util.py:128-157 (shift s counts if
    min(s, size - s) is a power of ``base``... precisely: index = s when
    s <= size//2 else size - s).
    """
    assert size > 0
    shifts = [0]
    for s in range(1, size):
        index = s if s <= size // 2 else size - s
        if _is_power_of(index, base):
            shifts.append(s)
    return _uniform_circulant(size, shifts)


def MeshGrid2DGraph(size: int, shape: Optional[Tuple[int, int]] = None) -> nx.DiGraph:
    """2-D grid graph with Metropolis-Hastings weights.

    Parity: reference topology_util.py:160-211 — 4-neighborhood grid with
    Hastings-rule weights w_ij = 1/max(deg_i, deg_j) (degree counts self),
    self weight = 2 - row sum.
    """
    assert size > 0
    if shape is None:
        nrow = int(np.sqrt(size))
        while size % nrow != 0:
            nrow -= 1
        shape = (nrow, size // nrow)
    nrow, ncol = shape
    assert nrow * ncol == size, "The shape doesn't match the size provided."

    connect = np.zeros((size, size))
    for i in range(size):
        connect[i, i] = 1.0
        if (i + 1) % ncol != 0:  # right neighbor in the same row
            connect[i, i + 1] = connect[i + 1, i] = 1.0
        if i + ncol < size:  # neighbor in the next row
            connect[i, i + ncol] = connect[i + ncol, i] = 1.0

    degree = [np.count_nonzero(connect[i]) for i in range(size)]  # incl. self
    weights = np.zeros((size, size))
    for i in range(size):
        for j in np.nonzero(connect[i])[0]:
            if i != j:
                weights[i, j] = 1.0 / max(degree[i], degree[j])
        weights[i, i] = 1.0 - weights[i].sum()
    return nx.from_numpy_array(weights, create_using=nx.DiGraph)


def StarGraph(size: int, center_rank: int = 0) -> nx.DiGraph:
    """Bidirectional star. Parity: reference topology_util.py:214-237."""
    assert size > 0
    weights = np.zeros((size, size))
    for i in range(size):
        weights[i, i] = 1.0 - 1.0 / size
        weights[center_rank, i] = 1.0 / size
        weights[i, center_rank] = 1.0 / size
    return nx.from_numpy_array(weights, create_using=nx.DiGraph)


def RingGraph(size: int, connect_style: int = 0) -> nx.DiGraph:
    """Ring graph. connect_style: 0 = bidirectional, 1 = left only,
    2 = right only. Parity: reference topology_util.py:240-281."""
    assert size > 0
    assert 0 <= connect_style <= 2, (
        "connect_style has to be int between 0 and 2, where 0 for "
        "bi-connection, 1 for left connection, 2 for right connection."
    )
    if size == 1:
        return circulant_graph(1, {0: 1.0})
    if size == 2:
        return circulant_graph(2, {0: 0.5, 1: 0.5})
    if connect_style == 0:
        return circulant_graph(size, {0: 1 / 3, 1: 1 / 3, size - 1: 1 / 3})
    if connect_style == 1:
        return circulant_graph(size, {0: 0.5, size - 1: 0.5})
    return circulant_graph(size, {0: 0.5, 1: 0.5})


def FullyConnectedGraph(size: int) -> nx.DiGraph:
    """All-to-all with uniform 1/size weights.
    Parity: reference topology_util.py:284-303."""
    assert size > 0
    return circulant_graph(size, {s: 1.0 / size for s in range(size)})


def IsTopologyEquivalent(topo1: Optional[nx.DiGraph], topo2: Optional[nx.DiGraph]) -> bool:
    """Weighted-adjacency equality (not isomorphism).
    Parity: reference topology_util.py:23-37."""
    if topo1 is None or topo2 is None:
        return False
    if topo1.number_of_nodes() != topo2.number_of_nodes():
        return False
    if topo1.number_of_edges() != topo2.number_of_edges():
        return False
    a1 = nx.to_numpy_array(topo1)
    a2 = nx.to_numpy_array(topo2)
    return bool((a1 == a2).all())


def IsRegularGraph(topo: nx.DiGraph) -> bool:
    """All nodes have the same (total) degree.
    Parity: reference topology_util.py:306-312."""
    degree = topo.degree(0)
    return all(topo.degree(r) == degree for r in range(1, topo.number_of_nodes()))


def GetRecvWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {src_rank: weight}) that ``rank`` applies when combining.
    Parity: reference topology_util.py:40-51."""
    weights = nx.to_numpy_array(topo)
    self_weight = 0.0
    neighbor_weights = {}
    for src in topo.predecessors(rank):
        if src == rank:
            self_weight = float(weights[rank, rank])
        else:
            neighbor_weights[src] = float(weights[src, rank])
    return self_weight, neighbor_weights


def GetSendWeights(topo: nx.DiGraph, rank: int) -> Tuple[float, Dict[int, float]]:
    """(self_weight, {dst_rank: weight}) read along ``rank``'s out-edges.
    Parity: reference topology_util.py:54-64."""
    weights = nx.to_numpy_array(topo)
    self_weight = 0.0
    neighbor_weights = {}
    for dst in topo.successors(rank):
        if dst == rank:
            self_weight = float(weights[rank, rank])
        else:
            neighbor_weights[dst] = float(weights[rank, dst])
    return self_weight, neighbor_weights
