"""Torus-aware dynamic schedules + machine-checked ICI congestion accounting.

Round-4 closure of the north-star routing gap: the scaling projection's
pessimistic bound previously charged a one-peer ``2^k`` rank shift
``min(2^k, n - 2^k)`` nearest-neighbor hops — a 1-D worst case that ignores
the physical interconnect.  A TPU v5e pod is a 2-D torus of ICI links (a
v5e-128 slice is an (8, 16) torus; ``jax.experimental.mesh_utils.
create_device_mesh`` hands out ranks in torus order), so the honest cost of
a permutation round is its **link congestion**: route every (src, dst) pair
along dimension-ordered minimal torus paths and take the maximum number of
payloads any single directed link carries.  Round wall-time =
``congestion x payload / link_bandwidth``.

This module provides
* the congestion counter (``link_loads`` / ``round_congestion``) — the
  machine-checked replacement for the closed-form hop guess, and
* ``torus_one_peer_schedule`` — one-peer dynamic rounds defined directly in
  torus coordinates, so the question "does the schedule map onto physical
  neighbors?" is answered by construction:

  - ``mode="single_hop"``: every round rotates the whole torus by one hop
    along one axis (2 rounds per axis, +/-).  Congestion is exactly 1 —
    the pessimistic routing model and the full-link-rate model coincide.
  - ``mode="exp2"``: per-axis exponential-2 shifts (the reference's
    one-peer Exponential-2 schedule, reference common/topology_util.py:
    315-357, re-indexed per torus axis).  With power-of-two axes and
    1/2-1/2 weights this reaches the EXACT average after
    ``sum(log2(axis))`` rounds — the hypercube dissemination argument,
    axis by axis — at a machine-counted mean congestion far below the
    1-D ``min(2^k, n-2^k)`` bound.

No jax imports: pure host-side schedule/analysis code (usable at
trace time and in CPU-only projection harnesses).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.topology.spec import DynamicTopology

__all__ = [
    "TorusSpec",
    "link_loads",
    "round_congestion",
    "schedule_congestion",
    "torus_one_peer_schedule",
    "torus_shift_round",
    "mixing_matrix",
    "consensus_contraction",
    "rounds_from_contraction",
    "rounds_to_consensus",
    "score_schedule",
    "default_pod_schedule",
]


@dataclasses.dataclass(frozen=True)
class TorusSpec:
    """Physical torus shape.  Rank r sits at the row-major coordinate
    ``unravel(r, axes)`` — the order ``mesh_utils.create_device_mesh``
    produces on a real slice, so logical rank i IS torus position i."""

    axes: Tuple[int, ...]

    @property
    def size(self) -> int:
        return int(np.prod(self.axes))

    def coord(self, rank: int) -> Tuple[int, ...]:
        return tuple(int(c) for c in np.unravel_index(rank, self.axes))

    def rank(self, coord: Sequence[int]) -> int:
        wrapped = [c % L for c, L in zip(coord, self.axes)]
        return int(np.ravel_multi_index(wrapped, self.axes))

    def is_neighbor(self, a: int, b: int) -> bool:
        """True iff a and b are one ICI hop apart (differ by +-1 mod L on
        exactly one axis)."""
        ca, cb = self.coord(a), self.coord(b)
        diff_axes = [i for i, (x, y) in enumerate(zip(ca, cb)) if x != y]
        if len(diff_axes) != 1:
            return False
        i = diff_axes[0]
        d = (cb[i] - ca[i]) % self.axes[i]
        return d == 1 or d == self.axes[i] - 1


def _axis_route(delta: int, length: int) -> List[Tuple[int, int, float]]:
    """Minimal-direction route groups for a displacement on one ring.

    Returns [(sign, hops, load_fraction)]: the payload goes ``hops`` hops
    in direction ``sign`` starting FROM THE SOURCE; when both directions
    are equally short (d == L/2) the payload is split half/half over the
    two opposite semicircles — the torus has both, and any reasonable
    router load-balances the tie."""
    d = delta % length
    if d == 0:
        return []
    back = length - d
    if d < back:
        return [(+1, d, 1.0)]
    if back < d:
        return [(-1, back, 1.0)]
    return [(+1, d, 0.5), (-1, back, 0.5)]


def link_loads(
    send_map,
    spec: TorusSpec,
    embedding: Optional[Sequence[int]] = None,
    payloads: Optional[Dict[Tuple[int, int], float]] = None,
) -> Dict[Tuple[Tuple[int, ...], int, int], float]:
    """Per-directed-link payload load of one exchange round under
    dimension-ordered minimal routing.

    ``send_map``: {src_rank: dst_rank} (one-peer rounds), or an iterable
    of ``(src, dst)`` pairs — the multi-shift form, where one src may
    send to several dsts in the same round (in-degree > 1 schedules;
    duplicate pairs accumulate).  Each pair routes one payload unless
    ``payloads[(src, dst)]`` scales it (the traffic-calibration path
    routes measured per-edge BYTES instead of unit payloads).
    ``embedding``: optional permutation; ``embedding[r]`` is the torus
    position of logical rank r (identity = row-major, the
    ``create_device_mesh`` order).  A link is keyed
    ``(node_coord, axis, sign)``: the link leaving ``node_coord`` along
    ``axis`` in direction ``sign``.
    """
    loads: Dict[Tuple[Tuple[int, ...], int, int], float] = {}
    emb = list(range(spec.size)) if embedding is None else list(embedding)
    pairs = (send_map.items() if isinstance(send_map, dict)
             else list(send_map))
    for src, dst in pairs:
        if src == dst:
            continue
        size = 1.0 if payloads is None else float(
            payloads.get((src, dst), 1.0))
        if size == 0.0:
            continue
        cur = list(spec.coord(emb[src]))
        tgt = spec.coord(emb[dst])
        for ax, L in enumerate(spec.axes):
            # each direction group walks from the SOURCE position of
            # this axis (a tie-split's two halves take opposite
            # semicircles; the -1 half must not retrace the +1 path)
            start = cur[ax]
            for sign, hops, frac in _axis_route(tgt[ax] - start, L):
                pos = start
                for _ in range(hops):
                    cur[ax] = pos
                    key = (tuple(cur), ax, sign)
                    loads[key] = loads.get(key, 0.0) + frac * size
                    pos = (pos + sign) % L
            cur[ax] = tgt[ax]
    return loads


def round_congestion(
    round_or_map,
    spec: TorusSpec,
    embedding: Optional[Sequence[int]] = None,
) -> float:
    """Maximum per-link load of one round (1.0 == a single payload at full
    link rate; the round's wall-time multiplier under the pessimistic,
    link-limited model).  Multi-shift ``DynamicTopology`` rounds
    (in-degree > 1) route EVERY declared edge — the loads add."""
    if isinstance(round_or_map, DynamicTopology):
        send_map = list(round_or_map.edges)
    elif isinstance(round_or_map, dict):
        send_map = dict(round_or_map)
    else:
        send_map = list(round_or_map)
    loads = link_loads(send_map, spec, embedding)
    return max(loads.values()) if loads else 0.0


def schedule_congestion(
    schedule: Iterable, spec: TorusSpec,
    embedding: Optional[Sequence[int]] = None,
) -> Dict[str, object]:
    """Machine-checked congestion profile of a dynamic schedule."""
    per_round = [round_congestion(r, spec, embedding) for r in schedule]
    return {
        "per_round": per_round,
        "mean": float(np.mean(per_round)) if per_round else 0.0,
        "max": float(np.max(per_round)) if per_round else 0.0,
    }


def torus_shift_round(
    spec: TorusSpec, axis: int, shift: int,
    self_weight: float = 0.5,
) -> DynamicTopology:
    """One round where every rank sends to the rank ``shift`` positions away
    along ``axis`` (a pure torus rotation: in-degree 1 everywhere)."""
    n = spec.size
    edge_weights: Dict[Tuple[int, int], float] = {}
    w = 1.0 - self_weight
    for src in range(n):
        c = list(spec.coord(src))
        c[axis] = (c[axis] + shift) % spec.axes[axis]
        dst = spec.rank(c)
        if dst != src:
            edge_weights[(src, dst)] = w
    return DynamicTopology.from_edges(n, edge_weights, [self_weight] * n)


def torus_one_peer_schedule(
    axes: Sequence[int], mode: str = "single_hop",
) -> List[DynamicTopology]:
    """One-peer dynamic schedule defined in physical torus coordinates.

    ``mode="single_hop"``: rounds cycle through the torus generators
    (+1 and -1 along each axis): every round is a one-ICI-hop rotation,
    congestion exactly 1.  Union over a period = the torus graph
    (strongly connected), weights 1/2-1/2 as in the reference's dynamic
    one-peer mode (reference torch/mpi_ops.py:504-510).

    ``mode="exp2"``: per-axis shifts of +2^k, k = 0..log2(L)-1 — the
    reference's Exponential-2 one-peer schedule applied along each torus
    axis.  For power-of-two axes, one period reaches the exact average
    (recursive pairwise halving per axis).
    """
    spec = TorusSpec(tuple(int(a) for a in axes))
    rounds: List[DynamicTopology] = []
    if mode == "single_hop":
        for axis in range(len(spec.axes)):
            if spec.axes[axis] < 2:
                continue
            rounds.append(torus_shift_round(spec, axis, +1))
            if spec.axes[axis] > 2:
                rounds.append(torus_shift_round(spec, axis, -1))
    elif mode == "exp2":
        for axis, L in enumerate(spec.axes):
            if L < 2:
                continue
            for k in range(max(1, int(math.log2(L)))):
                rounds.append(torus_shift_round(spec, axis, 2 ** k))
    else:
        raise ValueError(f"unknown torus schedule mode {mode!r}")
    return rounds


def mixing_matrix(rnd: DynamicTopology) -> np.ndarray:
    """Row-stochastic update matrix M with x_new = M @ x:
    ``M[dst, src]`` is the weight dst applies to src's value."""
    n = rnd.size
    M = np.zeros((n, n))
    for (src, dst), w in zip(rnd.edges, rnd.edge_weight_values):
        M[dst, src] = w
    M[np.arange(n), np.arange(n)] += np.asarray(rnd.self_weight_values)
    return M


def consensus_contraction(schedule: Sequence[DynamicTopology]) -> float:
    """Spectral contraction of one period: max |eigenvalue| of
    (P - 1 1^T / n) where P is the product of the per-round matrices.
    0.0 means the period reaches the exact average."""
    n = schedule[0].size
    P = np.eye(n)
    for rnd in schedule:
        P = mixing_matrix(rnd) @ P
    dev = P - np.full((n, n), 1.0 / n)
    return float(np.max(np.abs(np.linalg.eigvals(dev))))


def rounds_from_contraction(sigma: float, period: int,
                            eps: float = 1e-3) -> float:
    """Rounds to eps-consensus given one period's contraction sigma —
    the closed-form core of :func:`rounds_to_consensus`, public so the
    topology compiler's Fourier-scored candidates (which know sigma
    without building matrices) share the exact same figure of merit."""
    if sigma <= eps:  # exact (or better than eps) within one period
        return float(period)
    if sigma >= 1.0:
        return float("inf")
    return float(period * math.log(eps) / math.log(sigma))


_r2c_from_sigma = rounds_from_contraction  # internal alias (pre-PR name)


def rounds_to_consensus(
    schedule: Sequence[DynamicTopology], eps: float = 1e-3,
) -> float:
    """Rounds (not periods) for the disagreement to contract below eps.
    Exact-average periods report one period's length."""
    return _r2c_from_sigma(consensus_contraction(schedule), len(schedule),
                           eps)


def score_schedule(
    schedule: Sequence[DynamicTopology], spec: TorusSpec,
    eps: float = 1e-3,
) -> Dict[str, float]:
    """Machine-counted figures of merit for a one-peer schedule on a
    physical torus: per-STEP wire cost (mean link congestion — one round
    fires per training step, so this is the steady-state comm-time
    multiplier) and cost-to-consensus (summed congestion of the rounds a
    fresh disagreement needs to contract below ``eps`` — the statistical-
    efficiency axis the per-step number hides)."""
    cong = schedule_congestion(schedule, spec)
    sigma = consensus_contraction(schedule)  # once: O(period * n^3)
    period = len(schedule)
    r2c = _r2c_from_sigma(sigma, period, eps)
    return {
        "rounds_per_period": float(period),
        "mean_congestion": cong["mean"],
        "max_congestion": cong["max"],
        "rounds_to_consensus": r2c,
        "cost_to_consensus": cong["mean"] * r2c,
        "exact_average_per_period": float(sigma < 1e-12),
    }


def default_pod_schedule(
    axes: Sequence[int], eps: float = 1e-3, verbose: bool = False,
):
    """The documented default one-peer schedule for a pod's physical torus
    ``axes`` — picked by MACHINE-COUNTED score, not by rule of thumb.

    This two-entry menu is the floor, not the ceiling: for a real pod
    (heterogeneous DCN/ICI links, measured traffic) use
    ``topology.compiler.compile_topology``, which SEARCHES the weighted
    multi-shift schedule space and beats both menu entries at pod
    shapes (docs/topology.md).

    Candidates (all defined in torus coordinates, so every round's link
    congestion is exact, not a 1-D hop guess):

    * ``exp2``       — per-axis exponential-2 shifts: exact average each
      ``sum(log2(axis))``-round period, mean congestion ~2.3 on a
      near-square torus (the best-of-both-worlds schedule).
    * ``single_hop`` — one-ICI-hop rotations: congestion exactly 1 (the
      cheapest possible per-step wire time) but hundreds of rounds to
      consensus at pod scale.

    Selection: lowest ``cost_to_consensus`` (congestion-weighted rounds
    until a fresh disagreement contracts below ``eps``), tie-broken by
    per-step ``mean_congestion``.  On power-of-two tori this picks
    ``exp2``: ~16 congestion-units to the EXACT average vs single-hop's
    ~700 to 1e-3 — while its per-step cost (~2.3x single-hop) still
    projects >=95% scaling efficiency at v5e-128 with the int8 wire
    compressor (benchmarks/scaling_projection_r05.json).

    Returns ``(schedule, report)``: the winning round list (feed it to
    ``optim.functional.build_train_step(schedule=...)``, or iterate it
    as the per-step weight schedule for the eager
    ``api.neighbor_allreduce`` dynamic mode) and the per-candidate score
    table the choice was made from.
    """
    spec = TorusSpec(tuple(int(a) for a in axes))
    report = {}
    best_name, best_sched, best_key = None, None, None
    for mode in ("exp2", "single_hop"):
        sched = torus_one_peer_schedule(spec.axes, mode)
        if not sched:  # degenerate (all axes length 1)
            continue
        score = score_schedule(sched, spec, eps=eps)
        report[mode] = score
        key = (score["cost_to_consensus"], score["mean_congestion"])
        if best_key is None or key < best_key:
            best_name, best_sched, best_key = mode, sched, key
    if best_sched is None:
        raise ValueError(f"no non-trivial schedule for torus axes {axes!r}")
    for mode in report:
        report[mode]["selected"] = float(mode == best_name)
    if verbose:
        for mode, score in report.items():
            print(f"[default_pod_schedule] {mode}: {score}")
    return best_sched, report
