"""Global BlueFog-TPU context: mesh, topology state, eager op layer.

Replaces the reference's process-wide singleton ``BluefogGlobalState``
(reference: bluefog/common/global_state.h:44-117) and the ctypes facade
``BlueFogBasics`` (reference: bluefog/common/basics.py:37-568).  Where the
reference manages a background thread, tensor queue and rank-0 negotiation,
this context only holds: the device mesh (ranks == mesh positions), the
active topology specs, the window registry, and a cache of jitted
shard_map programs per (op, topology) pair.

Programming model
-----------------
BlueFog is rank-imperative (every MPI process calls ``bf.op(tensor)`` on its
own tensor).  The TPU-native equivalent is SPMD: **ranks are devices**; user
code runs once and operates on *rank-major global arrays* of shape
``[size, ...]`` sharded over the mesh axis, slice ``r`` being rank r's
tensor.  ``*_nonblocking`` returns a handle backed by JAX async dispatch
(the un-blocked jax.Array plays the role of the reference's
HandleManager promise, reference torch/handle_manager.h).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import networkx as nx
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bluefog_tpu import config as bfconfig
from bluefog_tpu.logging_util import get_logger
from bluefog_tpu.parallel import collectives as C
from bluefog_tpu.topology.graphs import ExponentialGraph
from bluefog_tpu.topology.spec import DynamicTopology, Topology

logger = get_logger()

AXIS = "bf"  # the rank axis name used by every eager program


class BluefogError(RuntimeError):
    pass


class _Heartbeat:
    """Per-process liveness beacon over the jax.distributed KV store.

    The reference's stall watchdog names the ranks a stalled tensor is
    still waiting on (operations.cc:388-433, from the coordinator's
    message table).  SPMD has no negotiation table, so liveness comes from
    heartbeats instead: every process periodically bumps a SEQUENCE
    NUMBER under ``bf_heartbeat_<pid>``; a stalled process scans all
    keys and names the processes whose sequence has not advanced.
    Sequence numbers (not wall times) make staleness a single-clock
    judgment — the observer compares its OWN monotonic clock across two
    of its own reads, so cross-host clock skew can neither falsely
    accuse a live rank nor mask a silent one."""

    KEY = "bf_heartbeat_{pid}"

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # pid -> (last seen sequence, observer-monotonic time it changed)
        self._seen: Dict[int, Tuple[int, float]] = {}

    @staticmethod
    def _client():
        try:
            from jax._src import distributed

            return distributed.global_state.client
        except Exception:
            return None

    def start(self, interval: float):
        client = self._client()
        if client is None or self._thread is not None:
            return
        me = jax.process_index()
        key = self.KEY.format(pid=me)

        def beat():
            seq = 1
            while not self._stop.wait(interval):
                try:
                    client.key_value_set(key, str(seq),
                                         allow_overwrite=True)
                    seq += 1
                except Exception:  # coordinator gone: job is ending
                    return

        client.key_value_set(key, "0", allow_overwrite=True)
        self._stop.clear()
        self._thread = threading.Thread(target=beat, daemon=True,
                                        name="bf-heartbeat")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def observe(self) -> None:
        """Record one observation of every process's sequence number.
        Called periodically by the watchdog loop while waits are active,
        building the history ``stale_processes`` judges against."""
        client = self._client()
        if client is None or jax.process_count() <= 1:
            return
        import time

        now = time.monotonic()
        for pid in range(jax.process_count()):
            try:
                seq = int(client.key_value_try_get(self.KEY.format(pid=pid)))
            except Exception:  # never wrote a beat
                seq = -1
            prev = self._seen.get(pid)
            if prev is None or prev[0] != seq:
                self._seen[pid] = (seq, now)

    def stale_processes(self, threshold: float) -> List[int]:
        """Processes whose sequence number has not advanced for
        ``threshold`` seconds of THIS process's monotonic clock (or who
        never wrote a beat).  Empty when liveness cannot be determined
        (single process / no KV store)."""
        client = self._client()
        if client is None or jax.process_count() <= 1:
            return []
        import time

        self.observe()
        now = time.monotonic()
        return [pid for pid, (seq, changed) in sorted(self._seen.items())
                if seq < 0 or now - changed > threshold]


_heartbeat = _Heartbeat()


class StallWatchdog:
    """Warns when a blocking wait runs longer than
    BLUEFOG_STALL_WARNING_TIME (reference stall watchdog: rank 0 prints
    tensors waiting >60 s AND which ranks they wait on,
    operations.cc:388-433 — rank attribution here comes from the
    heartbeat beacons).  One scanning thread for the whole process; waits
    register/unregister in a dict, so the per-op cost is a lock + dict
    write."""

    def __init__(self):
        self._lock = threading.Lock()
        self._waits: Dict[int, Tuple[str, float, int]] = {}
        self._next = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(target=self._loop,
                                                daemon=True)
                self._thread.start()

    def stop(self):
        """Stop the scanner thread (Event.set wakes it immediately) and join
        it, so a later watch() reliably restarts a fresh one."""
        self._stop.set()
        thread = self._thread
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)

    def _loop(self):
        import time

        while not self._stop.wait(min(5.0, max(0.05, bfconfig.stall_warning_time() / 4))):
            threshold = bfconfig.stall_warning_time()
            if threshold <= 0:
                continue
            now = time.monotonic()
            stalled = []
            with self._lock:
                has_waits = bool(self._waits)
                for token, (name, start, warned) in list(self._waits.items()):
                    elapsed = now - start
                    if elapsed > threshold * (warned + 1):
                        stalled.append((name, elapsed))
                        self._waits[token] = (name, start, warned + 1)
            if has_waits:
                # accumulate sequence observations while anything is
                # waiting, so a later stall has history to judge against
                _heartbeat.observe()
            # log OUTSIDE the lock: a slow log handler must not block the
            # register/unregister fast path of every wait
            if stalled:
                # 0.7x margin: the first observation of a frozen rank may
                # lag its actual freeze by up to one scan interval
                stale = _heartbeat.stale_processes(threshold * 0.7)
            for name, elapsed in stalled:
                if stale:
                    logger.warning(
                        "Stall detected: op '%s' has been waiting for "
                        "%.1f s on missing process(es) %s — their liveness "
                        "heartbeat is stale or absent (reference "
                        "operations.cc:388-433).", name, elapsed, stale)
                else:
                    logger.warning(
                        "Stall detected: op '%s' has been waiting for "
                        "%.1f s. One or more processes/devices may be "
                        "stuck or dead (reference operations.cc:388-433).",
                        name, elapsed)

    def watch(self, name: str):
        from contextlib import contextmanager

        @contextmanager
        def ctx():
            import time

            if bfconfig.stall_warning_time() <= 0:
                yield
                return
            with self._lock:
                token = self._next
                self._next += 1
                self._waits[token] = (name, time.monotonic(), 0)
            self._ensure_thread()
            try:
                yield
            finally:
                with self._lock:
                    self._waits.pop(token, None)

        return ctx()


_watchdog = StallWatchdog()


def timed_wait(name: str, wait_fn: Callable[[], Any]):
    """Run a blocking wait under the stall watchdog AND the hard op
    timeout (BLUEFOG_OP_TIMEOUT).

    With the timeout disabled (the default) this is exactly the old
    behavior: ``wait_fn()`` under a watchdog registration — stalls only
    warn.  With a timeout set, the wait runs on a helper thread; if it
    has not completed within the budget, a :class:`BluefogError` is
    raised naming the op and the stale processes the heartbeat beacons
    attribute the hang to (reference operations.cc:388-433 names the
    waited-on ranks; the reference then keeps waiting — this escalates).
    The helper thread cannot be interrupted and is leaked as a daemon;
    the caller is expected to tear the job down (the point of a hard
    timeout is to turn a silent hang into a crash an orchestrator can
    restart)."""
    timeout = bfconfig.op_timeout()
    if timeout <= 0:
        with _watchdog.watch(name):
            return wait_fn()
    box: Dict[str, Any] = {}
    done = threading.Event()

    def run():
        try:
            box["value"] = wait_fn()
        except BaseException as exc:  # noqa: BLE001 - relayed to caller
            box["error"] = exc
        finally:
            done.set()

    thread = threading.Thread(target=run, daemon=True,
                              name=f"bf-wait-{name}")
    with _watchdog.watch(name):
        thread.start()
        finished = done.wait(timeout)
    if not finished:
        # 0.7x margin mirrors the watchdog's stale attribution window
        stale = _heartbeat.stale_processes(timeout * 0.7)
        if stale:
            raise BluefogError(
                f"Operation '{name}' exceeded BLUEFOG_OP_TIMEOUT="
                f"{timeout:g} s; liveness heartbeats report stale/absent "
                f"process(es) {stale} — they are presumed dead or wedged.")
        raise BluefogError(
            f"Operation '{name}' exceeded BLUEFOG_OP_TIMEOUT={timeout:g} s "
            "with no stale heartbeat detected — the device queue itself "
            "may be wedged (or this is a single-process job, where "
            "liveness cannot be attributed).")
    if "error" in box:
        raise box["error"]
    return box.get("value")


def host_fetch(array) -> np.ndarray:
    """Materialize a (possibly multi-host-sharded) array on this host.

    On a single process this is ``np.asarray``; on a multi-process pod the
    remote shards are first gathered (np.asarray on a non-fully-addressable
    array raises)."""
    if jax.process_count() == 1:
        return np.asarray(array)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(array, tiled=True))


# Back-compat alias; the public home is bluefog_tpu.topology.spec.
from bluefog_tpu.topology.spec import (  # noqa: E402
    uniform_topology_spec as _uniform_topology_spec,
)


class WeightArg:
    """Normalized per-rank weight arguments for dynamic-topology calls.

    The reference takes per-rank ``self_weight: float``, ``src_weights:
    {src: w}``, ``dst_weights: {dst: w} | [dst]`` (reference
    torch/mpi_ops.py:545-660).  World-view SPMD accepts either one value used
    for all ranks, or a length-``size`` sequence of per-rank values.
    """

    @staticmethod
    def per_rank(value, size: int, kind: str) -> List:
        if value is None:
            return [None] * size
        if kind == "self":
            if isinstance(value, (int, float)):
                return [float(value)] * size
            value = list(value)
            if len(value) != size:
                raise ValueError(
                    f"per-rank self_weight needs length {size}, got {len(value)}"
                )
            return [float(v) for v in value]
        # src/dst weight maps: dict applies to every rank; a sequence gives
        # one entry per rank (each a dict, list, or None).
        if isinstance(value, dict):
            return [dict(value)] * size
        value = list(value)
        if len(value) != size:
            raise ValueError(
                f"per-rank {kind}_weights needs length {size}, got {len(value)}"
            )
        return [None if v is None else v for v in value]


class BluefogContext:
    """World state for one logical BlueFog job over a device mesh."""

    def __init__(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        local_size: Optional[int] = None,
    ):
        if devices is None:
            if bfconfig.ops_on_cpu():
                # BLUEFOG_OPS_ON_CPU: stage collectives on the host backend
                # (reference torch/mpi_ops.cc:48-50).
                devices = jax.devices("cpu")
            else:
                devices = jax.devices()
        self.devices = list(devices)
        self.mesh = Mesh(np.array(self.devices), (AXIS,))
        self._size = len(self.devices)

        addressable = [d for d in self.devices if d.process_index == jax.process_index()]
        self._process_rank0 = self.devices.index(addressable[0]) if addressable else 0
        # "machine" grouping: by default one machine per process; tests may
        # fake machines by passing local_size (mirrors the reference
        # hierarchical test fixture, test/torch_hierarchical_test.py:49-63).
        if local_size is None:
            local_size = len(addressable) if addressable else self._size
        if self._size % local_size != 0:
            raise BluefogError(
                f"local_size {local_size} must divide world size {self._size}"
            )
        self._local_size = local_size

        self._graph: Optional[nx.DiGraph] = None
        self._is_weighted = False
        self._topology: Optional[Topology] = None  # resolved combine weights
        self._machine_graph: Optional[nx.DiGraph] = None
        self._machine_is_weighted = False
        self._machine_topology: Optional[Topology] = None

        self._op_cache: Dict[Tuple, Callable] = {}
        self._handle_lock = threading.Lock()
        self._handle_map: Dict[int, Tuple[str, Any]] = {}
        self._inflight_names: set = set()
        self._timeline_open: Dict = {}  # span key -> tracer it began on
        self._next_handle = 0

        self.windows: Dict[str, Any] = {}  # name -> Window (windows.py)
        self.win_ops_with_associated_p = False
        self._skip_negotiate = bfconfig.skip_negotiate_default()
        self._suspended = False
        self.timeline = None  # attached by timeline module when enabled

    # ------------------------------------------------------------------ #
    # introspection (reference basics.py:78-265)
    # ------------------------------------------------------------------ #
    def size(self) -> int:
        return self._size

    def local_size(self) -> int:
        return self._local_size

    def rank(self) -> int:
        return self._process_rank0

    def local_rank(self) -> int:
        return self._process_rank0 % self._local_size

    def machine_size(self) -> int:
        return self._size // self._local_size

    def machine_rank(self) -> int:
        return self._process_rank0 // self._local_size

    def is_homogeneous(self) -> bool:
        return True  # mesh construction enforces equal local sizes

    # ------------------------------------------------------------------ #
    # topology management (reference basics.py:267-419)
    # ------------------------------------------------------------------ #
    def load_topology(self) -> nx.DiGraph:
        return self._graph

    def is_topo_weighted(self) -> bool:
        return self._is_weighted

    def set_topology(
        self, topology: Optional[nx.DiGraph] = None, is_weighted: bool = False
    ) -> bool:
        if topology is None:
            topology = ExponentialGraph(self._size)
        if not isinstance(topology, nx.DiGraph):
            logger.error("topology must be a networkx.DiGraph object.")
            return False
        if topology.number_of_nodes() != self._size:
            logger.error(
                "topology must have %d nodes, got %d.",
                self._size,
                topology.number_of_nodes(),
            )
            return False
        if self.windows:
            logger.error(
                "Cannot change topology with already registered windows: %s. "
                "Unregister them first.",
                list(self.windows),
            )
            return False
        self._graph = topology
        self._is_weighted = is_weighted
        spec = (
            Topology.from_graph(topology)
            if is_weighted
            else _uniform_topology_spec(topology)
        )
        self._topology = spec
        return True

    def load_machine_topology(self) -> nx.DiGraph:
        return self._machine_graph

    def is_machine_topo_weighted(self) -> bool:
        return self._machine_is_weighted

    def set_machine_topology(
        self, topology: Optional[nx.DiGraph], is_weighted: bool = False
    ) -> bool:
        if topology is None:
            logger.error("machine topology cannot be None.")
            return False
        if not isinstance(topology, nx.DiGraph):
            logger.error("machine topology must be a networkx.DiGraph object.")
            return False
        if topology.number_of_nodes() != self.machine_size():
            logger.error(
                "machine topology must have machine_size %d nodes, got %d.",
                self.machine_size(),
                topology.number_of_nodes(),
            )
            return False
        self._machine_graph = topology
        self._machine_is_weighted = is_weighted
        self._machine_topology = (
            Topology.from_graph(topology)
            if is_weighted
            else _uniform_topology_spec(topology)
        )
        return True

    def in_neighbor_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._graph is None:
            return []
        rank = self.rank() if rank is None else rank
        return sorted(s for s in self._graph.predecessors(rank) if s != rank)

    def out_neighbor_ranks(self, rank: Optional[int] = None) -> List[int]:
        if self._graph is None:
            return []
        rank = self.rank() if rank is None else rank
        return sorted(d for d in self._graph.successors(rank) if d != rank)

    def in_neighbor_machine_ranks(self, machine_rank: Optional[int] = None) -> List[int]:
        if self._machine_graph is None:
            return []
        m = self.machine_rank() if machine_rank is None else machine_rank
        return sorted(s for s in self._machine_graph.predecessors(m) if s != m)

    def out_neighbor_machine_ranks(self, machine_rank: Optional[int] = None) -> List[int]:
        if self._machine_graph is None:
            return []
        m = self.machine_rank() if machine_rank is None else machine_rank
        return sorted(d for d in self._machine_graph.successors(m) if d != m)

    def topology_spec(self) -> Topology:
        if self._topology is None:
            raise BluefogError("No topology set. Call bf.init() first.")
        return self._topology

    def machine_topology_spec(self) -> Topology:
        if self._machine_topology is None:
            raise BluefogError(
                "No machine topology set. Call bf.set_machine_topology() first."
            )
        return self._machine_topology

    # ------------------------------------------------------------------ #
    # rank-major array helpers
    # ------------------------------------------------------------------ #
    def rank_spec(self) -> NamedSharding:
        return NamedSharding(self.mesh, P(AXIS))

    def rank_sharded(self, array) -> jax.Array:
        """Shard an existing ``[size, ...]`` array over the rank axis.

        Multi-process: every process passes the same full host array; each
        contributes only its addressable shards (the SPMD contract — all
        processes execute the same program on the same logical values)."""
        if not isinstance(array, jax.Array) and jax.process_count() > 1:
            array = np.asarray(array)
            if array.shape[0] != self._size:
                raise BluefogError(
                    f"rank-major arrays need leading dim {self._size}, "
                    f"got {array.shape}")
            return jax.make_array_from_callback(
                array.shape, self.rank_spec(), lambda idx: array[idx])
        array = jnp.asarray(array)
        if array.shape[0] != self._size:
            raise BluefogError(
                f"rank-major arrays need leading dim {self._size}, got {array.shape}"
            )
        return jax.device_put(array, self.rank_spec())

    def from_rank_values(self, values) -> jax.Array:
        """Build a rank-major array from a callable ``rank -> np.ndarray`` or
        a sequence of per-rank arrays."""
        if callable(values):
            values = [values(r) for r in range(self._size)]
        stacked = np.stack([np.asarray(v) for v in values])
        return self.rank_sharded(stacked)

    def to_rank_values(self, array) -> List[np.ndarray]:
        return list(host_fetch(array))

    # ------------------------------------------------------------------ #
    # eager op execution
    # ------------------------------------------------------------------ #
    def _shardmapped(self, key: Tuple, kernel: Callable,
                     n_aux: int = 0) -> Callable:
        """Cache of jitted shard_map programs.  ``kernel`` maps a per-rank
        tensor (no leading rank axis) to a per-rank result; ``n_aux``
        extra operands (e.g. combine-weight vectors) are passed through
        REPLICATED, so their values stay out of the compile-cache key."""
        fn = self._op_cache.get(key)
        if fn is None:

            def wrapped(x, *aux):
                return kernel(x[0], *aux)[None]

            sm = jax.shard_map(
                wrapped, mesh=self.mesh,
                in_specs=(P(AXIS),) + (P(),) * n_aux, out_specs=P(AXIS),
                check_vma=False,
            )
            fn = jax.jit(sm)
            self._op_cache[key] = fn
        return fn

    def _op_tracer(self):
        """Where op spans go (``observe.tracer.effective_tracer``: the
        global tracer, or under ``BLUEFOG_OBSERVE=0`` the started
        timeline's private tracer, or None)."""
        from bluefog_tpu.observe.tracer import effective_tracer

        return effective_tracer(self.timeline)

    def run_op(self, key: Tuple, kernel: Callable, x, *aux) -> jax.Array:
        """Dispatch one eager collective.  Records the reference's
        ENQUEUE_<OP> span around the host-side dispatch (reference
        torch/mpi_ops.cc:178-488 starts the span at the binding,
        operations.cc:760 ends it when the background thread picks the
        entry up; here "enqueue" is trace-lookup + XLA dispatch) into
        the observe tracer, and counts the dispatch in
        ``bf_ops_total{op=}``."""
        from bluefog_tpu.observe import registry as obs_registry

        x = self.rank_sharded(x)
        op = str(key[0])
        if obs_registry.enabled():
            obs_registry.get_registry().counter(
                "bf_ops_total", "eager collective dispatches",
                op=op).inc()
        tr = self._op_tracer()
        if tr is None:
            return self._shardmapped(key, kernel, len(aux))(x, *aux)
        tr.begin(op, f"ENQUEUE_{op.upper()}")
        try:
            return self._shardmapped(key, kernel, len(aux))(x, *aux)
        finally:
            tr.end(op)

    # ------------------------------------------------------------------ #
    # handles (reference torch/handle_manager.{h,cc} + mpi_ops.py:947-1005)
    # ------------------------------------------------------------------ #
    def register_handle(self, name: Optional[str], op: str, value) -> int:
        with self._handle_lock:
            handle = self._next_handle
            self._next_handle += 1
            key = name if name is not None else f"{op}.noname.{handle}"
            if key in self._inflight_names:
                raise BluefogError(
                    f"Duplicate op name '{key}' is already in flight. "
                    "Use distinct names (reference common.h:181-185)."
                )
            self._inflight_names.add(key)
            self._handle_map[handle] = (key, value)
        # Per-tensor COMMUNICATE span with the data-plane op nested inside
        # (reference mpi_controller.cc:333,445 starts COMMUNICATE, the
        # vendor op name appears as MPI_<OP>; here the data plane is XLA,
        # so the nested span is XLA_<OP>).  The span runs from dispatch
        # until device completion is observed at synchronize/wait.
        tr = self._op_tracer()
        if tr is not None:
            tr.begin(key, "COMMUNICATE")
            tr.begin(key, f"XLA_{op.upper()}")
            # remember WHICH tracer the spans began on: a BLUEFOG_OBSERVE
            # flip between dispatch and synchronize must not send the E
            # records to a different tracer than the B records
            self._timeline_open[key] = tr
        return handle

    def synchronize(self, handle: int):
        with self._handle_lock:
            if handle not in self._handle_map:
                raise BluefogError(f"Unknown handle {handle}")
            key, value = self._handle_map.pop(handle)
            self._inflight_names.discard(key)
        try:
            return timed_wait(key, lambda: jax.block_until_ready(value))
        finally:
            # close spans even when the collective fails (a dead peer
            # raises here) — the trace must stay B/E-balanced precisely
            # in the failure case where it gets inspected
            tr = self._timeline_open.pop(key, None)
            if tr is not None:
                tr.end(key)  # XLA_<OP>
                tr.end(key)  # COMMUNICATE

    def poll(self, handle: int) -> bool:
        with self._handle_lock:
            if handle not in self._handle_map:
                raise BluefogError(f"Unknown handle {handle}")
            _, value = self._handle_map[handle]
        if hasattr(value, "raw"):  # _LazyResult wraps the device arrays
            value = value.raw
        leaves = jax.tree_util.tree_leaves(value)
        return all(leaf.is_ready() for leaf in leaves)

    def barrier(self):
        """Block the host until all dispatched device work completes.
        Reference: mpi_controller.cc:1185 / mpi_ops.py:1002-1005."""
        token = self.run_op(("barrier",), lambda x: C.allreduce(x, AXIS, False),
                            np.zeros((self._size, 1), np.int32))
        timed_wait("barrier", lambda: jax.block_until_ready(token))

    # ------------------------------------------------------------------ #
    # weight resolution for neighbor ops
    # ------------------------------------------------------------------ #
    def resolve_neighbor_spec(
        self,
        self_weight,
        src_weights,
        dst_weights,
        machine_level: bool = False,
        enable_topo_check: bool = False,
    ) -> Tuple[Union[Topology, DynamicTopology], bool]:
        """Mirror of the reference's weight-resolution ladder
        (torch/mpi_ops.py:484-535).  Returns (spec, dynamic_enabled).

        With ``enable_topo_check`` in dynamic mode, edges declared on only
        one side (a src_weights entry without the matching sender-side
        dst_weights entry, or vice versa) raise — the reference's collective
        send/recv pattern validation (mpi_controller.cc:364-417)."""
        n = self.machine_size() if machine_level else self._size
        graph = self._machine_graph if machine_level else self._graph
        static_spec = (
            self._machine_topology if machine_level else self._topology
        )

        if self_weight is None and src_weights is None and dst_weights is None:
            if static_spec is None:
                raise BluefogError("No topology set; call set_topology first.")
            return static_spec, False
        if (self_weight is None) != (src_weights is None):
            raise ValueError(
                "Arguments self_weight and src_weights have to be presented "
                "at the same time"
            )
        if self_weight is None and dst_weights is not None:
            raise ValueError(
                "Arguments self_weight and src_weights should be presented "
                "if enabling dynamic topology."
            )

        self_w = WeightArg.per_rank(self_weight, n, "self")
        src_w = WeightArg.per_rank(src_weights, n, "src")
        dst_w = WeightArg.per_rank(dst_weights, n, "dst")

        # Normalize dst entries to {dst: weight} (list => 1.0 weights,
        # reference torch/mpi_ops.py:497-500).
        dst_maps: List[Dict[int, float]] = []
        for r, entry in enumerate(dst_w):
            if entry is None:
                dst_maps.append({})
            elif isinstance(entry, dict):
                dst_maps.append({int(k): float(v) for k, v in entry.items()})
            else:
                lst = [int(v) for v in entry]
                if len(set(lst)) != len(lst):
                    raise ValueError(
                        "Argument dst_weights should only contain the unique ranks."
                    )
                dst_maps.append({v: 1.0 for v in lst})

        dynamic = dst_weights is not None
        weight_matrix = None
        if graph is not None and any(sw is None for sw in src_w):
            weight_matrix = nx.to_numpy_array(graph)
        edge_weights: Dict[Tuple[int, int], float] = {}
        claimed_recv_edges = set()
        for dst in range(n):
            sw = src_w[dst]
            if sw is None:
                if weight_matrix is None:
                    raise BluefogError("No topology set; call set_topology first.")
                sw = {
                    int(s): float(weight_matrix[s, dst])
                    for s in np.nonzero(weight_matrix[:, dst])[0]
                    if s != dst
                }
            if not isinstance(sw, dict):
                raise ValueError(
                    "Argument src_weights has to be a dictionary map from the "
                    "(in-)neighbor rank to the weights."
                )
            for src, w in sw.items():
                src = int(src)
                scale = 1.0
                if dynamic:
                    if src >= len(dst_maps):
                        raise ValueError(f"src rank {src} out of range")
                    claimed_recv_edges.add((src, dst))
                    if dst not in dst_maps[src]:
                        if enable_topo_check:
                            raise BluefogError(
                                f"Send and recv neighbors mismatch: rank {dst} "
                                f"expects from {src}, but {src} does not list "
                                f"{dst} in dst_weights "
                                "(reference mpi_controller.cc:364-417)."
                            )
                        continue  # src does not send to dst this round
                    scale = dst_maps[src][dst]
                edge_weights[(src, dst)] = float(w) * scale
        if dynamic and enable_topo_check:
            for src, dmap in enumerate(dst_maps):
                for dst in dmap:
                    if (src, int(dst)) not in claimed_recv_edges:
                        raise BluefogError(
                            f"Send and recv neighbors mismatch: rank {src} "
                            f"sends to {dst}, but {dst} does not list {src} "
                            "in src_weights "
                            "(reference mpi_controller.cc:364-417)."
                        )
        selfs = [
            (sw if sw is not None else 0.0) for sw in self_w
        ]
        spec = DynamicTopology.from_edges(n, edge_weights, selfs)
        return spec, dynamic

    # ------------------------------------------------------------------ #
    # misc parity shims
    # ------------------------------------------------------------------ #
    def suspend(self):
        self._suspended = True

    def resume(self):
        self._suspended = False

    def set_skip_negotiate_stage(self, value: bool):
        # There is no negotiation stage on TPU (SPMD makes readiness static);
        # kept for API parity (reference operations.cc:1149-1183).
        self._skip_negotiate = bool(value)

    def get_skip_negotiate_stage(self) -> bool:
        return self._skip_negotiate


_global_context: Optional[BluefogContext] = None


def get_context() -> BluefogContext:
    if _global_context is None:
        raise BluefogError(
            "BlueFog-TPU has not been initialized; call bluefog_tpu.init() first."
        )
    return _global_context


def set_context(ctx: Optional[BluefogContext]):
    global _global_context
    _global_context = ctx


def is_initialized() -> bool:
    return _global_context is not None
