"""TensorFlow bridge — tf tensors over the BlueFog-TPU data plane.

Genuine counterpart of the reference's TensorFlow binding (reference
bluefog/tensorflow/mpi_ops.{py,cc}: allreduce / allgather / broadcast
custom ops with gradient registration; bluefog/tensorflow/optimizers.py:
``DistributedOptimizer``, ``DistributedGradientTape``,
``broadcast_variables``) — the surface a TF user of the reference
migrates onto.  Like the torch bridge, it accepts **rank-major tensors**
(``[n_ranks, ...]``, host-resident) and converts through numpy; the
jitted JAX path remains the performance surface.

GRAPH MODE: inside ``tf.function`` / compiled Keras ``model.fit`` the
ops lower to ``tf.py_function`` nodes (reference parity: the
reference's TF custom ops run inside TF graphs,
tensorflow/mpi_ops.cc:1-235) — the graph calls back into the eager
numpy bridge at execution time.  PERFORMANCE CAVEAT, stated as loudly
as docs/interop.md does for torch: every op is still a host
round-trip (device->host->JAX->host->device), in eager AND graph
mode.  This surface is a correctness/migration bridge; the jitted
JAX path is the performance surface.  Direct calls on symbolic
tensors outside the provided ops raise in ``_to_jax``.

Gradient flow matches the reference's registered gradients:
``allreduce``'s gradient is an allreduce (reference mpi_ops.py:95-106),
``broadcast``'s is a reduction onto the root (reference :163-178), and
``allgather``'s slices the gathered cotangent back per rank (reference
:204-230).  Implemented with ``tf.custom_gradient`` over a numpy bridge
instead of C++ custom ops — under SPMD there is no per-rank op to bind.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import bluefog_tpu as bf

try:  # tensorflow is an optional dependency of this module only
    import tensorflow as tf
except ImportError:  # pragma: no cover
    tf = None

__all__ = [
    "allreduce", "allgather", "broadcast", "neighbor_allreduce",
    "broadcast_variables", "DistributedOptimizer",
    "DistributedGradientTape", "TFAdapter",
]


def _require_tf():
    if tf is None:
        raise ImportError(
            "bluefog_tpu.interop.tf_adapter requires tensorflow")


def _to_jax(tensor):
    import jax

    _require_tf()
    if not tf.executing_eagerly():
        # symbolic tensors have no .numpy(); the module's public ops
        # route graph-mode calls through tf.py_function (see _bridge),
        # which re-enters eager execution — only a direct _to_jax on a
        # symbolic tensor can land here
        raise RuntimeError(
            "bluefog_tpu.interop.tf_adapter: got a symbolic tensor "
            "outside tf.py_function. Use the adapter's public ops "
            "(they wrap graph-mode calls in tf.py_function) or call "
            "eagerly.")
    if not tf.is_tensor(tensor):
        tensor = tf.convert_to_tensor(tensor)
    if (tensor.dtype in (tf.float64, tf.int64)
            and not jax.config.jax_enable_x64):
        raise TypeError(
            f"{tensor.dtype.name} tensors need jax_enable_x64; enable it "
            "or cast to a 32-bit dtype first")
    return bf.rank_sharded(tensor.numpy())


def _to_tf(array, like=None):
    host = np.asarray(array)
    out = tf.convert_to_tensor(host)
    if like is not None and tf.is_tensor(like):
        out = tf.cast(out, like.dtype)
    return out


def _bridge(eager_fn, x, out_shape=None):
    """Run ``eager_fn`` (the numpy/JAX bridge) on ``x`` now if eager, or
    as a ``tf.py_function`` graph node if tracing — the reference's TF
    custom ops run inside graphs (reference tensorflow/mpi_ops.py:77-230);
    py_function is the TPU build's equivalent graph hook, with the same
    host round-trip the eager path already takes."""
    if tf.executing_eagerly():
        return eager_fn(x)
    y = tf.py_function(eager_fn, [x], Tout=x.dtype)
    y.set_shape(x.shape if out_shape is None else out_shape)
    return y


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Rank-major tf tensor -> global (average) reduction.  Differentiable:
    the pulled-back cotangent is itself allreduced (reference
    tensorflow/mpi_ops.py:95-106)."""
    _require_tf()

    @tf.custom_gradient
    def _op(x):
        y = _bridge(
            lambda t: _to_tf(bf.allreduce(_to_jax(t), average=average,
                                          name=name), like=t), x)

        def grad(dy):
            return _bridge(
                lambda t: _to_tf(bf.allreduce(_to_jax(t), average=average),
                                 like=t), dy)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    """Every rank's slice becomes the root's.  Gradient: cotangents
    reduce onto the root slice, zeros elsewhere (reference
    tensorflow/mpi_ops.py:163-178)."""
    _require_tf()

    @tf.custom_gradient
    def _op(x):
        y = _bridge(
            lambda t: _to_tf(bf.broadcast(_to_jax(t), root_rank,
                                          name=name), like=t), x)

        def grad(dy):
            def _g(t):
                summed = bf.allreduce(_to_jax(t), average=False)
                g = np.zeros_like(np.asarray(summed))
                g[root_rank] = np.asarray(summed)[root_rank]
                return _to_tf(g, like=t)

            return _bridge(_g, dy)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def allgather(tensor, name: Optional[str] = None):
    """Concatenate all ranks' slices along dim 0 (per rank).  Gradient:
    each rank keeps its own slice of the cotangent, summed over the
    ranks that received it (reference tensorflow/mpi_ops.py:204-230)."""
    _require_tf()

    @tf.custom_gradient
    def _op(x):
        # output is [n, n*rows, ...] for [n, rows, ...] input; keep every
        # statically-unknown dim unknown rather than stamping the input
        # shape (rank<2 is rejected by the eager path at runtime)
        if x.shape.rank is not None and x.shape.rank > 1:
            n_static, rows_static = x.shape[0], x.shape[1]
            mid = (n_static * rows_static
                   if n_static is not None and rows_static is not None
                   else None)
            gathered = tf.TensorShape(
                [n_static, mid]).concatenate(x.shape[2:])
        else:
            gathered = tf.TensorShape(None)
        y = _bridge(
            lambda t: _to_tf(bf.allgather(_to_jax(t), name=name), like=t),
            x, out_shape=gathered)

        def grad(dy):
            n = bf.size()
            rows = tf.shape(x)[1]
            # dy is rank-major [n, n*rows, ...]: every rank j received a
            # copy of rank r's slice, so dL/dx[r] sums the cotangents all
            # ranks produced for that slice (the reference lowers this as
            # allreduce + slice-own-part, mpi_ops.py:204-230; rank-major
            # host tensors make it one reshape-sum)
            dy_split = tf.reshape(
                dy, tf.concat([[n, n, rows], tf.shape(dy)[2:]], axis=0))
            return tf.cast(tf.reduce_sum(dy_split, axis=0), dy.dtype)

        return y, grad

    return _op(tf.convert_to_tensor(tensor))


def neighbor_allreduce(tensor, *, self_weight=None, src_weights=None,
                       dst_weights=None, enable_topo_check: bool = True,
                       name: Optional[str] = None):
    """Weighted neighbor combine (the op the reference's TF binding never
    had — its TF users were limited to allreduce; exposed here so the TF
    surface reaches capability parity with the torch one)."""
    _require_tf()
    return _bridge(
        lambda t: _to_tf(
            bf.neighbor_allreduce(_to_jax(t), self_weight=self_weight,
                                  src_weights=src_weights,
                                  dst_weights=dst_weights,
                                  enable_topo_check=enable_topo_check,
                                  name=name),
            like=t),
        tf.convert_to_tensor(tensor))


def broadcast_variables(variables, root_rank: int = 0):
    """In-place: assign every variable its root-rank slice (reference
    tensorflow/optimizers.py:64-85 broadcast_variables)."""
    _require_tf()
    for var in variables:
        var.assign(broadcast(var, root_rank))


class DistributedOptimizer:
    """Wrap a ``tf.keras.optimizers.Optimizer`` over rank-major replica
    stacks (reference tensorflow/optimizers.py:88-162).

    * ``communication="allreduce"``: average gradients globally before
      ``apply_gradients`` (the reference TF binding's only mode).
    * ``communication="neighbor_allreduce"``: apply the base step, then
      combine variables with graph neighbors (ATC) — the decentralized
      flavor the reference reserves for torch, exposed to TF here.
    """

    def __init__(self, optimizer, communication: str = "allreduce"):
        _require_tf()
        if communication not in ("allreduce", "neighbor_allreduce"):
            raise ValueError(f"unknown communication {communication!r}")
        self.optimizer = optimizer
        self.communication = communication

    def apply_gradients(self, grads_and_vars, *args, **kwargs):
        grads_and_vars = list(grads_and_vars)
        if self.communication == "allreduce":
            grads_and_vars = [
                (g if g is None else allreduce(g, average=True), v)
                for g, v in grads_and_vars]
        result = self.optimizer.apply_gradients(grads_and_vars, *args,
                                                **kwargs)
        if self.communication == "neighbor_allreduce":
            for _, v in grads_and_vars:
                v.assign(neighbor_allreduce(v))
        return result

    def apply(self, grads, trainable_variables=None, **kwargs):
        """Keras-3 entry point (``Model.train_step`` calls
        ``optimizer.apply``): route through the communicating
        ``apply_gradients`` so a compiled ``model.fit`` still averages
        gradients / combines neighbors."""
        if trainable_variables is None:
            # Keras-3 one-arg form: the built optimizer knows its
            # variables; bare grads must NOT reach apply_gradients
            # (it unpacks (grad, var) pairs)
            trainable_variables = getattr(
                self.optimizer, "_trainable_variables", None)
            if not trainable_variables:
                raise ValueError(
                    "apply(grads) without trainable_variables requires "
                    "the wrapped optimizer to be built; pass "
                    "trainable_variables explicitly")
        return self.apply_gradients(
            list(zip(grads, trainable_variables)), **kwargs)

    def minimize(self, loss, var_list, tape=None):
        """Route through the communicating ``apply_gradients`` — the
        base optimizer's ``minimize`` would silently skip it."""
        if callable(loss):
            with tf.GradientTape() as inner:
                value = loss()
            grads = inner.gradient(value, var_list)
        else:
            if tape is None:
                raise ValueError(
                    "minimize() with a loss tensor requires tape=")
            grads = tape.gradient(loss, var_list)
        self.apply_gradients(zip(grads, var_list))

    def __getattr__(self, name):
        if name == "optimizer" or (name.startswith("__")
                                   and name.endswith("__")):
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "optimizer"), name)


class DistributedGradientTape:
    """``tf.GradientTape`` wrapper whose ``gradient()`` allreduces the
    results (reference tensorflow/optimizers.py:165-196)."""

    def __init__(self, tape):
        _require_tf()
        self.tape = tape

    def __enter__(self):
        self.tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self.tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self.tape.gradient(target, sources, output_gradients)
        flat = tf.nest.flatten(grads)
        flat = [g if g is None else allreduce(g, average=True)
                for g in flat]
        return tf.nest.pack_sequence_as(grads, flat)

    def __getattr__(self, name):
        if name == "tape" or (name.startswith("__")
                              and name.endswith("__")):
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "tape"), name)


class TFAdapter:
    """Module-style facade mirroring the reference's
    ``bluefog.tensorflow`` API object."""

    allreduce = staticmethod(allreduce)
    allgather = staticmethod(allgather)
    broadcast = staticmethod(broadcast)
    neighbor_allreduce = staticmethod(neighbor_allreduce)
    broadcast_variables = staticmethod(broadcast_variables)
    DistributedOptimizer = DistributedOptimizer
    DistributedGradientTape = DistributedGradientTape
