"""HuggingFace Llama checkpoint import.

A user of the reference framework trains torch models; a user switching
to this framework will want to start from published weights.  This
module converts a ``transformers`` ``LlamaForCausalLM`` (model object or
raw ``state_dict``) into the param tree of :class:`bluefog_tpu.models.
Llama` — both the unrolled (``layer_{i}``) and scanned
(``scan_layers=True``, stacked ``[n_layers]``) layouts — so any of this
framework's parallel layouts (dp/tp/ep/pp/sp share one param TREE) can
start from HF weights via ``llama_param_specs`` + ``rank_major``.

Rotary convention: HF stores q/k projections in the "half-split" rotary
layout (``rotate_half``), while this framework (like the original Meta
weights) uses the interleaved even/odd pairing — the conversion inverse-
permutes the q/k rows, after which logits match ``transformers``' output
to float32 roundoff (tests/test_hf_import.py).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping

import jax.numpy as jnp
import numpy as np

from bluefog_tpu.models.llama import LlamaConfig

__all__ = ["llama_config_from_hf", "llama_params_from_hf"]


def llama_config_from_hf(hf_config, **overrides) -> LlamaConfig:
    """Map a ``transformers.LlamaConfig`` onto this framework's config.
    Compute/layout knobs (dtype, attn_mode, scan_layers, tp/ep/pp axes…)
    are orthogonal to the checkpoint and passed through ``overrides``.

    ``rope_type='llama3'`` scaling (Llama-3.1+) maps onto the model's
    ``rope_scaling_*`` fields; other scaling kinds and projection biases
    raise — a silent pass-through would convert mainstream checkpoints
    into a model whose logits quietly diverge from ``transformers``."""
    rope_scaling = getattr(hf_config, "rope_scaling", None)
    scaling_fields = {}
    if rope_scaling not in (None, {}):
        kind = rope_scaling.get("rope_type",
                                rope_scaling.get("type", None))
        if kind == "default":
            pass  # explicit no-op scaling
        elif kind == "llama3":
            # all four sub-fields are REQUIRED (transformers validates
            # them too): silently assuming a default here would convert
            # into a model whose logits quietly diverge — the exact
            # failure this importer exists to prevent
            required = ("factor", "low_freq_factor", "high_freq_factor",
                        "original_max_position_embeddings")
            missing = [k for k in required if k not in rope_scaling]
            if missing:
                raise ValueError(
                    f"rope_scaling={rope_scaling!r} is missing required "
                    f"llama3 field(s) {missing}; refusing to guess — "
                    "the scaled frequencies would silently diverge "
                    "from transformers'.")
            scaling_fields = dict(
                rope_scaling_kind="llama3",
                rope_scaling_factor=float(rope_scaling["factor"]),
                rope_scaling_low_freq_factor=float(
                    rope_scaling["low_freq_factor"]),
                rope_scaling_high_freq_factor=float(
                    rope_scaling["high_freq_factor"]),
                rope_scaling_original_max_len=int(
                    rope_scaling["original_max_position_embeddings"]))
        else:
            raise NotImplementedError(
                f"rope_scaling={rope_scaling!r} is not supported: only "
                "rope_type='llama3' (Llama-3.1 style) frequency scaling "
                "is implemented; other kinds would make the converted "
                "model's logits quietly diverge from transformers'.")
    for flag in ("attention_bias", "mlp_bias"):
        if getattr(hf_config, flag, False):
            raise NotImplementedError(
                f"{flag}=True is not supported: this framework's "
                "projections are bias-free, so the bias tensors would "
                "be silently dropped.")
    base = dict(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads",
                           hf_config.num_attention_heads),
        hidden_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(getattr(hf_config, "rope_theta", 10000.0)),
        norm_eps=float(hf_config.rms_norm_eps),
        **scaling_fields,
    )
    base.update(overrides)
    return LlamaConfig(**base)


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().float().numpy()
    return np.asarray(t, dtype=np.float32)


def _unpermute_rotary(w: np.ndarray, n_heads: int, dim: int) -> np.ndarray:
    """HF's checkpoint converter permutes q/k rows from the original
    interleaved rotary layout to its half-split (``rotate_half``) layout
    via ``w.view(H, hd//2, 2, D).transpose(1, 2)``; this is the inverse,
    restoring the interleaved pairing this framework's ``rotary_embed``
    uses."""
    out_dim = w.shape[0]
    hd = out_dim // n_heads
    return (w.reshape(n_heads, 2, hd // 2, dim)
            .transpose(0, 2, 1, 3)
            .reshape(out_dim, dim))


def llama_params_from_hf(model_or_state_dict, cfg: LlamaConfig,
                         dtype=jnp.float32) -> Dict[str, Any]:
    """Convert HF ``LlamaForCausalLM`` weights to a ``{"params": ...}``
    tree for ``models.Llama(cfg)``.  ``cfg.scan_layers`` picks the
    layout: unrolled ``layer_{i}`` modules or one stacked
    ``layers/block`` tree with a leading ``[n_layers]`` axis."""
    import jax

    sd: Mapping[str, Any]
    if hasattr(model_or_state_dict, "state_dict"):
        sd = model_or_state_dict.state_dict()
    else:
        sd = dict(model_or_state_dict)

    # Per-leaf conversion: each tensor is cast to the target dtype and
    # placed on device individually, so the host-RAM peak stays ~1x the
    # checkpoint (an eager whole-dict f32 copy would peak at 3-4x and
    # OOM the host at 8B scale).
    def take(name, transform=None):
        a = _to_np(sd[name])
        if transform is not None:
            a = transform(a)
        return jnp.asarray(a, dtype)

    def kernel(name):  # torch Linear stores [out, in]; flax Dense [in, out]
        return take(name, lambda a: a.T)

    hd = cfg.head_dim
    q0 = sd["model.layers.0.self_attn.q_proj.weight"]
    k0 = sd["model.layers.0.self_attn.k_proj.weight"]
    assert (tuple(q0.shape) == (cfg.n_heads * hd, cfg.dim)
            and tuple(k0.shape) == (cfg.n_kv_heads * hd, cfg.dim)), (
        "state_dict geometry does not match cfg (heads/dim/kv_heads)")

    def layer_tree(i: int) -> Dict[str, Any]:
        pre = f"model.layers.{i}."
        return {
            "attention": {
                "wq": {"kernel": take(
                    pre + "self_attn.q_proj.weight",
                    lambda a: _unpermute_rotary(a, cfg.n_heads, cfg.dim).T)},
                "wk": {"kernel": take(
                    pre + "self_attn.k_proj.weight",
                    lambda a: _unpermute_rotary(a, cfg.n_kv_heads,
                                                cfg.dim).T)},
                "wv": {"kernel": kernel(pre + "self_attn.v_proj.weight")},
                "wo": {"kernel": kernel(pre + "self_attn.o_proj.weight")},
            },
            "attention_norm": {
                "scale": take(pre + "input_layernorm.weight")},
            "feed_forward": {
                "w1": {"kernel": kernel(pre + "mlp.gate_proj.weight")},
                "w3": {"kernel": kernel(pre + "mlp.up_proj.weight")},
                "w2": {"kernel": kernel(pre + "mlp.down_proj.weight")},
            },
            "ffn_norm": {
                "scale": take(pre + "post_attention_layernorm.weight")},
        }

    layers = [layer_tree(i) for i in range(cfg.n_layers)]
    if cfg.scan_layers:
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls, axis=0), *layers)
        layer_part = {"layers": {"block": stacked}}
    else:
        layer_part = {f"layer_{i}": layers[i] for i in range(cfg.n_layers)}

    head_name = ("lm_head.weight" if "lm_head.weight" in sd
                 else "model.embed_tokens.weight")  # tied embeddings
    params = {
        "tok_embeddings": {"embedding": take("model.embed_tokens.weight")},
        **layer_part,
        "norm": {"scale": take("model.norm.weight")},
        "output": {"kernel": kernel(head_name)},
    }
    return {"params": params}
