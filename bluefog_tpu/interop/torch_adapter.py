"""PyTorch bridge — torch tensors over the BlueFog-TPU data plane.

Capability parity with the reference's second-framework binding layer
(reference bluefog/tensorflow/{adapter,mpi_ops}.cc + mpi_ops.py: a reduced
op surface — allreduce / broadcast / (neighbor_)allreduce — exposed to a
framework other than the primary one).  Here the primary surface is JAX;
this adapter accepts **rank-major torch tensors** (``[n_ranks, ...]``,
CPU) and returns torch tensors, converting through numpy (one host copy
each way).

This is host-side interop for experimentation and porting — the tensors
round-trip through the host, so the jitted JAX path remains the
performance surface.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import bluefog_tpu as bf

try:  # torch is an optional dependency of this module only
    import torch
except ImportError:  # pragma: no cover
    torch = None


def _require_torch():
    if torch is None:
        raise ImportError(
            "bluefog_tpu.interop.torch_adapter requires torch")


def _to_jax(tensor):
    import jax

    _require_torch()
    if not isinstance(tensor, torch.Tensor):
        raise TypeError(f"expected a torch.Tensor, got {type(tensor)}")
    if (tensor.dtype in (torch.float64, torch.int64)
            and not jax.config.jax_enable_x64):
        # Without x64, JAX would silently truncate to 32 bits and the
        # round-trip back to the torch dtype would hide the damage.
        raise TypeError(
            f"{tensor.dtype} tensors need jax_enable_x64; enable it or "
            "cast to a 32-bit dtype first")
    return bf.rank_sharded(np.asarray(tensor.detach().cpu().contiguous()))


def _to_torch(array, like=None):
    host = np.asarray(array)
    out = torch.from_numpy(np.ascontiguousarray(host))
    if like is not None:
        out = out.to(like.dtype)
    return out


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Rank-major torch tensor -> global (average) reduction."""
    return _to_torch(bf.allreduce(_to_jax(tensor), average=average,
                                  name=name), like=tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return _to_torch(bf.broadcast(_to_jax(tensor), root_rank, name=name),
                     like=tensor)


def allgather(tensor, name: Optional[str] = None):
    return _to_torch(bf.allgather(_to_jax(tensor), name=name), like=tensor)


def neighbor_allreduce(tensor, *, self_weight=None, src_weights=None,
                       dst_weights=None, enable_topo_check: bool = True,
                       name: Optional[str] = None):
    return _to_torch(
        bf.neighbor_allreduce(_to_jax(tensor), self_weight=self_weight,
                              src_weights=src_weights,
                              dst_weights=dst_weights,
                              enable_topo_check=enable_topo_check,
                              name=name),
        like=tensor)


def broadcast_parameters(tensors, root_rank: int = 0):
    """In-place: every rank's slice of each rank-major tensor becomes the
    root rank's slice (reference tensorflow ``broadcast_variables`` /
    torch ``broadcast_parameters``, torch/utility.py:26)."""
    _require_torch()
    with torch.no_grad():
        for t in tensors:
            t.copy_(broadcast(t, root_rank))


class DistributedOptimizer:
    """Wrap a ``torch.optim.Optimizer`` whose parameters are rank-major
    ``[n_ranks, ...]`` replica stacks; communication runs over the
    BlueFog-TPU data plane.

    Mirrors the reference's second-framework optimizer surface
    (reference tensorflow/optimizers.py DistributedOptimizer — gradient
    allreduce) plus the decentralized flavor:

    * ``communication="allreduce"``: average gradients globally before
      the base step (Horovod-style).
    * ``communication="neighbor_allreduce"``: take the base step, then
      combine parameters with graph neighbors (ATC).
    """

    def __init__(self, optimizer, communication: str = "allreduce"):
        _require_torch()
        if communication not in ("allreduce", "neighbor_allreduce"):
            raise ValueError(f"unknown communication {communication!r}")
        self.optimizer = optimizer
        self.communication = communication

    def _params(self):
        for group in self.optimizer.param_groups:
            for p in group["params"]:
                yield p

    def step(self, closure=None):
        with torch.no_grad():
            if self.communication == "allreduce":
                for p in self._params():
                    if p.grad is not None:
                        p.grad.copy_(allreduce(p.grad, average=True))
        loss = self.optimizer.step(closure)
        with torch.no_grad():
            if self.communication == "neighbor_allreduce":
                for p in self._params():
                    p.data.copy_(neighbor_allreduce(p.data))
        return loss

    def zero_grad(self, *a, **kw):
        return self.optimizer.zero_grad(*a, **kw)

    def __getattr__(self, name):
        # Guard against infinite recursion when 'optimizer' itself is
        # missing (pickling/copy protocols probe dunders before __init__
        # has run) — raise AttributeError instead of recursing.
        if name == "optimizer" or (name.startswith("__")
                                   and name.endswith("__")):
            raise AttributeError(name)
        return getattr(object.__getattribute__(self, "optimizer"), name)


class TorchAdapter:
    """Module-style facade mirroring the reference's framework API object —
    the same reduced surface its TF binding exposes (allreduce, allgather,
    broadcast, DistributedOptimizer, broadcast_variables; reference
    tensorflow/mpi_ops.py, tensorflow/optimizers.py) plus
    neighbor_allreduce."""

    allreduce = staticmethod(allreduce)
    allgather = staticmethod(allgather)
    broadcast = staticmethod(broadcast)
    neighbor_allreduce = staticmethod(neighbor_allreduce)
    broadcast_parameters = staticmethod(broadcast_parameters)
    broadcast_variables = staticmethod(broadcast_parameters)  # TF name
    DistributedOptimizer = DistributedOptimizer
