"""PyTorch bridge — torch tensors over the BlueFog-TPU data plane.

Capability parity with the reference's second-framework binding layer
(reference bluefog/tensorflow/{adapter,mpi_ops}.cc + mpi_ops.py: a reduced
op surface — allreduce / broadcast / (neighbor_)allreduce — exposed to a
framework other than the primary one).  Here the primary surface is JAX;
this adapter accepts **rank-major torch tensors** (``[n_ranks, ...]``,
CPU) and returns torch tensors, converting through numpy (one host copy
each way).

This is host-side interop for experimentation and porting — the tensors
round-trip through the host, so the jitted JAX path remains the
performance surface.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import bluefog_tpu as bf

try:  # torch is an optional dependency of this module only
    import torch
except ImportError:  # pragma: no cover
    torch = None


def _require_torch():
    if torch is None:
        raise ImportError(
            "bluefog_tpu.interop.torch_adapter requires torch")


def _to_jax(tensor):
    import jax

    _require_torch()
    if not isinstance(tensor, torch.Tensor):
        raise TypeError(f"expected a torch.Tensor, got {type(tensor)}")
    if (tensor.dtype in (torch.float64, torch.int64)
            and not jax.config.jax_enable_x64):
        # Without x64, JAX would silently truncate to 32 bits and the
        # round-trip back to the torch dtype would hide the damage.
        raise TypeError(
            f"{tensor.dtype} tensors need jax_enable_x64; enable it or "
            "cast to a 32-bit dtype first")
    return bf.rank_sharded(np.asarray(tensor.detach().cpu().contiguous()))


def _to_torch(array, like=None):
    host = np.asarray(array)
    out = torch.from_numpy(np.ascontiguousarray(host))
    if like is not None:
        out = out.to(like.dtype)
    return out


def allreduce(tensor, average: bool = True, name: Optional[str] = None):
    """Rank-major torch tensor -> global (average) reduction."""
    return _to_torch(bf.allreduce(_to_jax(tensor), average=average,
                                  name=name), like=tensor)


def broadcast(tensor, root_rank: int, name: Optional[str] = None):
    return _to_torch(bf.broadcast(_to_jax(tensor), root_rank, name=name),
                     like=tensor)


def allgather(tensor, name: Optional[str] = None):
    return _to_torch(bf.allgather(_to_jax(tensor), name=name), like=tensor)


def neighbor_allreduce(tensor, *, self_weight=None, src_weights=None,
                       dst_weights=None, enable_topo_check: bool = True,
                       name: Optional[str] = None):
    return _to_torch(
        bf.neighbor_allreduce(_to_jax(tensor), self_weight=self_weight,
                              src_weights=src_weights,
                              dst_weights=dst_weights,
                              enable_topo_check=enable_topo_check,
                              name=name),
        like=tensor)


class TorchAdapter:
    """Module-style facade mirroring the reference's framework API object —
    the same reduced surface its TF binding exposes (allreduce, allgather,
    broadcast; reference tensorflow/mpi_ops.py) plus neighbor_allreduce."""

    allreduce = staticmethod(allreduce)
    allgather = staticmethod(allgather)
    broadcast = staticmethod(broadcast)
    neighbor_allreduce = staticmethod(neighbor_allreduce)
