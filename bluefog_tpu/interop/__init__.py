"""Framework adapters.

The reference maintains a second framework binding beside torch (its
TensorFlow custom ops + DistributedOptimizer, reference
bluefog/tensorflow/).  The TPU build's second surface is a **PyTorch
bridge**: torch tensors in, torch tensors out, with the JAX/XLA data plane
underneath (host round-trip through numpy).
"""

from bluefog_tpu.interop.torch_adapter import (  # noqa: F401
    DistributedOptimizer,
    TorchAdapter,
    allgather,
    allreduce,
    broadcast,
    broadcast_parameters,
    neighbor_allreduce,
)
from bluefog_tpu.interop.hf_llama import (  # noqa: F401
    llama_config_from_hf,
    llama_params_from_hf,
)
