"""Framework adapters.

The reference binds two frameworks (torch + TensorFlow custom ops,
reference bluefog/torch/, bluefog/tensorflow/).  The TPU build's primary
surface is JAX; BOTH a **PyTorch bridge** and a **TensorFlow bridge**
are provided (framework tensors in/out, the JAX/XLA data plane
underneath, one numpy host round-trip each way).  The torch names are
re-exported flat for compatibility; the TF surface lives under
``interop.tf`` / ``bluefog_tpu.interop.tf_adapter``.
"""

from bluefog_tpu.interop.torch_adapter import (  # noqa: F401
    DistributedOptimizer,
    TorchAdapter,
    allgather,
    allreduce,
    broadcast,
    broadcast_parameters,
    neighbor_allreduce,
)


def __getattr__(name):
    # PEP 562 lazy import: touching interop.tf / TFAdapter is what pays
    # TensorFlow's multi-second import, not `import bluefog_tpu.interop`.
    # importlib (not `from ... import`) avoids re-entering this hook.
    if name in ("tf", "tf_adapter"):
        import importlib

        return importlib.import_module("bluefog_tpu.interop.tf_adapter")
    if name == "TFAdapter":
        import importlib

        mod = importlib.import_module("bluefog_tpu.interop.tf_adapter")
        return mod.TFAdapter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
from bluefog_tpu.interop.hf_llama import (  # noqa: F401
    llama_config_from_hf,
    llama_params_from_hf,
)
