// BlueFog-TPU native data-loading engine.
//
// The reference leans on torch's C++ DataLoader for input (its examples all
// iterate torch DataLoaders); this build supplies its own native input
// pipeline: a multi-threaded batch-gather engine that fills a ring of
// pre-allocated host buffers and hands batches to Python in order.
//
// Division of labor: Python computes WHAT to load (per-epoch index order,
// sharding, shuffling — cheap integer work, and keeping it in one place
// makes the native and pure-Python paths bit-identical); C++ does the HOW
// (the memcpy gather of scattered records into contiguous batch buffers,
// overlapped with compute by worker threads and a depth-deep slot ring).
//
// Concurrency model:
//   * jobs = batch indices, claimed by workers from an atomic counter;
//   * batch b lands in slot b % depth; a worker waits until that slot has
//     been released by the consumer (its previous tenant was b - depth);
//   * the consumer takes batches strictly in order (slot of next_out_),
//     then releases the slot when Python is done with the buffer;
//   * start_epoch quiesces in-flight fills (epoch tag + active counter),
//     resets the ring, installs the new index order.
//
// Build: compiled into libbf_native.so together with bf_native.cc.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<uint8_t> buf;  // all fields, field f at field_offset[f]
  int64_t batch_id = -1;     // which batch is resident (-1: none)
  int64_t count = 0;         // samples in the resident batch
  int64_t turn = 0;          // next batch id this slot may accept
  bool ready = false;        // filled, not yet consumed
  bool free_ = true;         // released by consumer, fillable
};

class DataPipeline {
 public:
  DataPipeline(int n_fields, const uint8_t* const* field_ptrs,
               const int64_t* field_item_bytes, int64_t n_items,
               int64_t batch, int depth, int workers)
      : n_items_(n_items), batch_(batch), depth_(depth) {
    fields_.assign(field_ptrs, field_ptrs + n_fields);
    item_bytes_.assign(field_item_bytes, field_item_bytes + n_fields);
    int64_t off = 0;
    for (int f = 0; f < n_fields; ++f) {
      field_offset_.push_back(off);
      off += batch_ * item_bytes_[f];
    }
    slot_bytes_ = off;
    slots_.resize(depth_);
    for (auto& s : slots_) s.buf.resize(slot_bytes_);
    for (int w = 0; w < workers; ++w)
      threads_.emplace_back([this] { WorkerLoop(); });
  }

  ~DataPipeline() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_) t.join();
  }

  // Install a new epoch's index order.  Blocks until in-flight fills from
  // the previous epoch have retired; any unconsumed batches are dropped.
  void StartEpoch(const int64_t* order, int64_t n) {
    std::unique_lock<std::mutex> lk(mu_);
    epoch_ += 1;              // in-flight fills see a stale tag and discard
    n_batches_ = 0;           // no new claims
    cv_.wait(lk, [this] { return active_fills_ == 0; });
    order_.assign(order, order + n);
    n_order_ = n;
    for (size_t i = 0; i < slots_.size(); ++i) {
      Slot& s = slots_[i];
      s.batch_id = -1;
      s.count = 0;
      s.turn = static_cast<int64_t>(i);  // slot i serves i, i+depth, ...
      s.ready = false;
      s.free_ = true;
    }
    next_job_ = 0;
    next_out_ = 0;
    n_batches_ = (n + batch_ - 1) / batch_;
    lk.unlock();
    cv_.notify_all();
  }

  int64_t NumBatches() const { return n_batches_; }

  // Returns the slot holding the next batch (blocks), or -1 at epoch end.
  int64_t Next() {
    std::unique_lock<std::mutex> lk(mu_);
    if (next_out_ >= n_batches_) return -1;
    const int64_t want = next_out_;
    Slot& s = slots_[want % depth_];
    cv_.wait(lk, [&] {
      return stop_ || (s.ready && s.batch_id == want);
    });
    if (stop_) return -1;
    next_out_ += 1;
    return want % depth_;
  }

  void Release(int64_t slot) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      Slot& s = slots_[slot];
      s.ready = false;
      s.free_ = true;
      if (s.batch_id >= 0) s.turn = s.batch_id + depth_;
      s.batch_id = -1;
    }
    cv_.notify_all();
  }

  const uint8_t* SlotPtr(int64_t slot, int field) const {
    return slots_[slot].buf.data() + field_offset_[field];
  }

  int64_t SlotCount(int64_t slot) const { return slots_[slot].count; }

 private:
  void WorkerLoop() {
    for (;;) {
      int64_t b, my_epoch;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || next_job_ < n_batches_; });
        if (stop_) return;
        b = next_job_++;
        my_epoch = epoch_;
        Slot& s = slots_[b % depth_];
        // wait for the consumer to vacate this slot AND for this batch's
        // turn: with more workers than slots, the worker holding batch
        // b + depth could otherwise seize the slot before batch b, and
        // the in-order consumer would wait forever
        cv_.wait(lk, [&] {
          return stop_ || epoch_ != my_epoch || (s.free_ && s.turn == b);
        });
        if (stop_) return;
        if (epoch_ != my_epoch) continue;  // epoch reset stole the job
        s.free_ = false;
        active_fills_ += 1;
      }
      Fill(b, my_epoch);
    }
  }

  void Fill(int64_t b, int64_t my_epoch) {
    Slot& s = slots_[b % depth_];
    const int64_t start = b * batch_;
    const int64_t count = std::min(batch_, n_order_ - start);
    // the gather itself runs without the lock — this is the heavy part
    for (size_t f = 0; f < fields_.size(); ++f) {
      const int64_t ib = item_bytes_[f];
      uint8_t* dst = s.buf.data() + field_offset_[f];
      const uint8_t* src_base = fields_[f];
      for (int64_t i = 0; i < count; ++i)
        std::memcpy(dst + i * ib, src_base + order_[start + i] * ib, ib);
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      active_fills_ -= 1;
      if (epoch_ == my_epoch) {
        s.batch_id = b;
        s.count = count;
        s.ready = true;
      } else {
        s.free_ = true;  // stale fill: slot back to the pool
      }
    }
    cv_.notify_all();
  }

  std::vector<const uint8_t*> fields_;
  std::vector<int64_t> item_bytes_;
  std::vector<int64_t> field_offset_;
  int64_t n_items_;
  int64_t batch_;
  int64_t depth_;
  int64_t slot_bytes_ = 0;

  std::vector<Slot> slots_;
  std::vector<std::thread> threads_;
  std::vector<int64_t> order_;
  int64_t n_order_ = 0;
  int64_t n_batches_ = 0;
  int64_t next_job_ = 0;
  int64_t next_out_ = 0;
  int64_t epoch_ = 0;
  int64_t active_fills_ = 0;
  bool stop_ = false;
  std::mutex mu_;
  std::condition_variable cv_;
};

}  // namespace

extern "C" {

void* bfdata_create(int n_fields, const uint8_t* const* field_ptrs,
                    const int64_t* field_item_bytes, int64_t n_items,
                    int64_t batch, int depth, int workers) {
  if (n_fields <= 0 || batch <= 0 || depth <= 0 || workers <= 0)
    return nullptr;
  return new DataPipeline(n_fields, field_ptrs, field_item_bytes, n_items,
                          batch, depth, workers);
}

void bfdata_start_epoch(void* h, const int64_t* order, int64_t n) {
  if (h != nullptr)
    static_cast<DataPipeline*>(h)->StartEpoch(order, n);
}

long long bfdata_num_batches(void* h) {
  return h != nullptr ? static_cast<DataPipeline*>(h)->NumBatches() : -1;
}

long long bfdata_next(void* h) {
  return h != nullptr ? static_cast<DataPipeline*>(h)->Next() : -1;
}

void bfdata_release(void* h, long long slot) {
  if (h != nullptr) static_cast<DataPipeline*>(h)->Release(slot);
}

const uint8_t* bfdata_slot_ptr(void* h, long long slot, int field) {
  return h != nullptr
             ? static_cast<DataPipeline*>(h)->SlotPtr(slot, field)
             : nullptr;
}

long long bfdata_slot_count(void* h, long long slot) {
  return h != nullptr ? static_cast<DataPipeline*>(h)->SlotCount(slot) : -1;
}

void bfdata_destroy(void* h) {
  delete static_cast<DataPipeline*>(h);
}

}  // extern "C"
