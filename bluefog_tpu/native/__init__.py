"""Native (C++) runtime components, loaded via ctypes.

The reference's native layer bridges framework tensors to MPI/NCCL; on TPU
XLA supplies the data plane, so the native components here are the runtime
pieces AROUND the compute path (SURVEY.md §7.9): currently the Chrome-
tracing timeline writer (lock-free SPSC ring + writer thread, mirroring
reference common/timeline.{h,cc}).

The shared library is built lazily with g++ on first use and cached next to
the source; every consumer must degrade gracefully when ``available()`` is
False (no compiler, exotic platform).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "bf_native.cc")
_LIB = os.path.join(_HERE, "libbf_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # per-process temp name: concurrent ranks (bfrun) may build at once and
    # must not clobber each other's output mid-write
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", "-o", tmp,
           _SRC, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120,
                       text=True)
        os.replace(tmp, _LIB)
        return True
    except subprocess.CalledProcessError as exc:
        _log_build_failure(exc.stderr)
        return False
    except (OSError, subprocess.SubprocessError) as exc:
        _log_build_failure(str(exc))
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _log_build_failure(detail: str):
    from bluefog_tpu.logging_util import get_logger

    get_logger().warning(
        "native library build failed; falling back to Python "
        "implementations. Compiler output:\n%s", detail)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        stale = (not os.path.exists(_LIB) or
                 os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if stale and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.bf_timeline_open.restype = ctypes.c_void_p
        lib.bf_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.bf_timeline_record.restype = None
        lib.bf_timeline_record.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char]
        lib.bf_timeline_dropped.restype = ctypes.c_longlong
        lib.bf_timeline_dropped.argtypes = [ctypes.c_void_p]
        lib.bf_timeline_close.restype = None
        lib.bf_timeline_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeTimelineWriter:
    """ctypes facade over the C++ TimelineWriter.  Single-producer: callers
    must serialize Record calls (the Python Timeline holds a lock)."""

    def __init__(self, path: str, rank: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._dropped_at_close = 0
        self._handle = lib.bf_timeline_open(path.encode(), rank)
        if not self._handle:
            raise OSError(f"cannot open timeline file {path}")

    def record(self, name: str, tid: str, phase: str):
        self._lib.bf_timeline_record(
            self._handle, name.encode(), tid.encode(), phase.encode())

    def dropped(self) -> int:
        if not self._handle:
            return self._dropped_at_close
        return int(self._lib.bf_timeline_dropped(self._handle))

    def close(self):
        if self._handle:
            self._dropped_at_close = int(
                self._lib.bf_timeline_dropped(self._handle))
            self._lib.bf_timeline_close(self._handle)
            self._handle = None
