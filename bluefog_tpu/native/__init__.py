"""Native (C++) runtime components, loaded via ctypes.

The reference's native layer bridges framework tensors to MPI/NCCL; on TPU
XLA supplies the data plane, so the native components here are the runtime
pieces AROUND the compute path (SURVEY.md §7.9):

* ``bf_native.cc`` — Chrome-tracing timeline writer (lock-free SPSC ring +
  writer thread, mirroring reference common/timeline.{h,cc});
* ``bf_data.cc`` — batch-gather data engine (worker pool filling a ring of
  pre-allocated host batch buffers; the input pipeline the reference gets
  from torch's C++ DataLoader).

The shared library is built lazily with g++ on first use and cached next to
the source; every consumer must degrade gracefully when ``available()`` is
False (no compiler, exotic platform).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_HERE, "bf_native.cc"),
         os.path.join(_HERE, "bf_data.cc")]
_LIB = os.path.join(_HERE, "libbf_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # per-process temp name: concurrent ranks (bfrun) may build at once and
    # must not clobber each other's output mid-write
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = ["g++", "-std=c++17", "-O2", "-shared", "-fPIC", "-o", tmp,
           *_SRCS, "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120,
                       text=True)
        os.replace(tmp, _LIB)
        return True
    except subprocess.CalledProcessError as exc:
        _log_build_failure(exc.stderr)
        return False
    except (OSError, subprocess.SubprocessError) as exc:
        _log_build_failure(str(exc))
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def _log_build_failure(detail: str):
    from bluefog_tpu.logging_util import get_logger

    get_logger().warning(
        "native library build failed; falling back to Python "
        "implementations. Compiler output:\n%s", detail)


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        stale = (not os.path.exists(_LIB) or
                 os.path.getmtime(_LIB) < max(os.path.getmtime(s)
                                              for s in _SRCS))
        if stale and not _build():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.bf_timeline_open.restype = ctypes.c_void_p
        lib.bf_timeline_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.bf_timeline_record.restype = None
        lib.bf_timeline_record.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char]
        lib.bf_timeline_dropped.restype = ctypes.c_longlong
        lib.bf_timeline_dropped.argtypes = [ctypes.c_void_p]
        lib.bf_timeline_close.restype = None
        lib.bf_timeline_close.argtypes = [ctypes.c_void_p]
        lib.bfdata_create.restype = ctypes.c_void_p
        lib.bfdata_create.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int]
        lib.bfdata_start_epoch.restype = None
        lib.bfdata_start_epoch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
        lib.bfdata_num_batches.restype = ctypes.c_longlong
        lib.bfdata_num_batches.argtypes = [ctypes.c_void_p]
        lib.bfdata_next.restype = ctypes.c_longlong
        lib.bfdata_next.argtypes = [ctypes.c_void_p]
        lib.bfdata_release.restype = None
        lib.bfdata_release.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.bfdata_slot_ptr.restype = ctypes.c_void_p
        lib.bfdata_slot_ptr.argtypes = [
            ctypes.c_void_p, ctypes.c_longlong, ctypes.c_int]
        lib.bfdata_slot_count.restype = ctypes.c_longlong
        lib.bfdata_slot_count.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.bfdata_destroy.restype = None
        lib.bfdata_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


class NativeTimelineWriter:
    """ctypes facade over the C++ TimelineWriter.  Single-producer: callers
    must serialize Record calls (the Python Timeline holds a lock)."""

    def __init__(self, path: str, rank: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._dropped_at_close = 0
        self._handle = lib.bf_timeline_open(path.encode(), rank)
        if not self._handle:
            raise OSError(f"cannot open timeline file {path}")

    def record(self, name: str, tid: str, phase: str):
        self._lib.bf_timeline_record(
            self._handle, name.encode(), tid.encode(), phase.encode())

    def dropped(self) -> int:
        if not self._handle:
            return self._dropped_at_close
        return int(self._lib.bf_timeline_dropped(self._handle))

    def close(self):
        if self._handle:
            self._dropped_at_close = int(
                self._lib.bf_timeline_dropped(self._handle))
            self._lib.bf_timeline_close(self._handle)
            self._handle = None


class NativeBatchPipeline:
    """ctypes facade over the C++ DataPipeline (bf_data.cc): multi-threaded
    gather of scattered records into a depth-deep ring of contiguous batch
    buffers, delivered strictly in order.

    ``fields`` are C-contiguous numpy arrays sharing a leading sample dim;
    the caller must keep them alive for the pipeline's lifetime (this class
    holds references).  Buffers returned by ``next()`` are views into ring
    slots — valid only until ``release(slot)``.
    """

    def __init__(self, fields, batch_size: int, depth: int = 3,
                 workers: int = 2):
        import numpy as np

        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self._fields = [np.ascontiguousarray(f) for f in fields]
        n = self._fields[0].shape[0]
        for f in self._fields:
            if f.shape[0] != n:
                raise ValueError("all fields need the same sample count")
        self._batch = int(batch_size)
        self._item_shapes = [f.shape[1:] for f in self._fields]
        self._dtypes = [f.dtype for f in self._fields]
        item_bytes = [int(f.nbytes // max(n, 1)) for f in self._fields]
        ptrs = (ctypes.c_void_p * len(fields))(
            *[f.ctypes.data_as(ctypes.c_void_p).value for f in self._fields])
        bts = (ctypes.c_int64 * len(fields))(*item_bytes)
        self._handle = lib.bfdata_create(
            len(fields), ptrs, bts, n, self._batch, depth, workers)
        if not self._handle:
            raise RuntimeError("bfdata_create failed")

    def start_epoch(self, order) -> int:
        """Install this epoch's sample-index order; returns batch count."""
        import numpy as np

        order = np.ascontiguousarray(order, dtype=np.int64)
        self._lib.bfdata_start_epoch(
            self._handle, order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(order))
        return int(self._lib.bfdata_num_batches(self._handle))

    def next(self):
        """Blocking: (slot, [field views]) or None at epoch end."""
        import numpy as np

        slot = int(self._lib.bfdata_next(self._handle))
        if slot < 0:
            return None
        count = int(self._lib.bfdata_slot_count(self._handle, slot))
        views = []
        for f, (shape, dtype) in enumerate(
                zip(self._item_shapes, self._dtypes)):
            ptr = self._lib.bfdata_slot_ptr(self._handle, slot, f)
            nbytes = count * int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
            raw = (ctypes.c_uint8 * nbytes).from_address(ptr)
            views.append(np.frombuffer(raw, dtype=dtype).reshape(
                (count,) + tuple(shape)))
        return slot, views

    def release(self, slot: int):
        self._lib.bfdata_release(self._handle, slot)

    def close(self):
        if getattr(self, "_handle", None):
            self._lib.bfdata_destroy(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
