// BlueFog-TPU native runtime components.
//
// Chrome-tracing timeline writer: a lock-free single-producer/single-
// consumer ring buffer drained by a dedicated writer thread — the same
// design as the reference's C++ TimelineWriter over a boost::lockfree
// spsc_queue (reference bluefog/common/timeline.h:46-122, timeline.cc),
// rebuilt from scratch with C++11 atomics and no third-party deps.
//
// The producer side must be a single thread (the Python wrapper holds a
// lock); the consumer is the writer thread started at open.
//
// Build: g++ -std=c++17 -O2 -shared -fPIC -o libbf_native.so bf_native.cc -lpthread

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

namespace {

constexpr size_t kNameLen = 96;
constexpr size_t kRingSize = 1 << 15;  // events; power of two

struct Event {
  char name[kNameLen];
  char tid[kNameLen];
  char ph;        // 'B' begin, 'E' end, 'i' instant
  double ts_us;   // microseconds since open
};

// SPSC ring buffer: head written by producer, tail by consumer.
class Ring {
 public:
  bool push(const Event& e) {
    const uint64_t h = head_.load(std::memory_order_relaxed);
    const uint64_t t = tail_.load(std::memory_order_acquire);
    if (h - t >= kRingSize) return false;  // full -> caller drops
    buf_[h & (kRingSize - 1)] = e;
    head_.store(h + 1, std::memory_order_release);
    return true;
  }

  bool pop(Event* e) {
    const uint64_t t = tail_.load(std::memory_order_relaxed);
    const uint64_t h = head_.load(std::memory_order_acquire);
    if (t == h) return false;
    *e = buf_[t & (kRingSize - 1)];
    tail_.store(t + 1, std::memory_order_release);
    return true;
  }

 private:
  std::atomic<uint64_t> head_{0};
  std::atomic<uint64_t> tail_{0};
  Event buf_[kRingSize];
};

void JsonEscape(const char* in, char* out, size_t out_len) {
  size_t j = 0;
  for (size_t i = 0; in[i] != '\0' && j + 2 < out_len; ++i) {
    const char c = in[i];
    if (c == '"' || c == '\\') out[j++] = '\\';
    if (static_cast<unsigned char>(c) < 0x20) continue;  // drop control chars
    out[j++] = c;
  }
  out[j] = '\0';
}

class TimelineWriter {
 public:
  TimelineWriter(const char* path, int rank)
      : file_(std::fopen(path, "w")), rank_(rank),
        t0_(std::chrono::steady_clock::now()) {
    if (file_ != nullptr) {
      std::fputs("[\n", file_);
      thread_ = std::thread([this] { Loop(); });
    }
  }

  bool ok() const { return file_ != nullptr; }

  double NowUs() const {
    return std::chrono::duration<double, std::micro>(
               std::chrono::steady_clock::now() - t0_).count();
  }

  void Record(const char* name, const char* tid, char ph) {
    Event e;
    std::snprintf(e.name, kNameLen, "%s", name != nullptr ? name : "");
    std::snprintf(e.tid, kNameLen, "%s", tid != nullptr ? tid : "");
    e.ph = ph;
    e.ts_us = NowUs();
    if (!ring_.push(e)) dropped_.fetch_add(1, std::memory_order_relaxed);
  }

  int64_t Dropped() const {
    return dropped_.load(std::memory_order_relaxed);
  }

  void Close() {
    if (file_ == nullptr) return;
    stop_.store(true, std::memory_order_release);
    if (thread_.joinable()) thread_.join();
    std::fputs("\n]\n", file_);
    std::fclose(file_);
    file_ = nullptr;
  }

  ~TimelineWriter() { Close(); }

 private:
  void Loop() {
    Event e;
    char name_esc[2 * kNameLen];
    char tid_esc[2 * kNameLen];
    while (true) {
      bool got = ring_.pop(&e);
      if (!got) {
        if (stop_.load(std::memory_order_acquire)) {
          if (!ring_.pop(&e)) break;  // fully drained
          got = true;
        } else {
          std::this_thread::sleep_for(std::chrono::milliseconds(1));
          continue;
        }
      }
      JsonEscape(e.name, name_esc, sizeof(name_esc));
      JsonEscape(e.tid, tid_esc, sizeof(tid_esc));
      if (!first_) std::fputs(",\n", file_);
      first_ = false;
      if (e.ph == 'i') {
        std::fprintf(file_,
                     "{\"name\": \"%s\", \"ph\": \"i\", \"ts\": %.3f, "
                     "\"pid\": %d, \"s\": \"p\"}",
                     name_esc, e.ts_us, rank_);
      } else if (e.ph == 'B') {
        std::fprintf(file_,
                     "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"B\", "
                     "\"ts\": %.3f, \"pid\": %d, \"tid\": \"%s\"}",
                     name_esc, tid_esc, e.ts_us, rank_, tid_esc);
      } else {
        std::fprintf(file_,
                     "{\"ph\": \"E\", \"ts\": %.3f, \"pid\": %d, "
                     "\"tid\": \"%s\"}",
                     e.ts_us, rank_, tid_esc);
      }
      if ((++written_ & 0xFF) == 0) std::fflush(file_);
    }
  }

  std::FILE* file_;
  int rank_;
  std::chrono::steady_clock::time_point t0_;
  Ring ring_;
  std::thread thread_;
  std::atomic<bool> stop_{false};
  std::atomic<int64_t> dropped_{0};
  bool first_ = true;
  uint64_t written_ = 0;
};

}  // namespace

extern "C" {

void* bf_timeline_open(const char* path, int rank) {
  auto* w = new TimelineWriter(path, rank);
  if (!w->ok()) {
    delete w;
    return nullptr;
  }
  return w;
}

void bf_timeline_record(void* handle, const char* name, const char* tid,
                        char ph) {
  if (handle != nullptr)
    static_cast<TimelineWriter*>(handle)->Record(name, tid, ph);
}

long long bf_timeline_dropped(void* handle) {
  return handle != nullptr
             ? static_cast<TimelineWriter*>(handle)->Dropped()
             : -1;
}

void bf_timeline_close(void* handle) {
  if (handle != nullptr) {
    auto* w = static_cast<TimelineWriter*>(handle);
    w->Close();
    delete w;
  }
}

int bf_native_abi_version() { return 1; }

}  // extern "C"
