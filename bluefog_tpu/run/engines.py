"""Native interactive engines — ``ibfrun``'s self-contained backend.

The reference's ``ibfrun`` drives notebook workflows through ipyparallel
(reference bluefog/run/interactive_run.py: ipcontroller + mpirun'd
ipengines + ``%%px``).  ipyparallel is an optional external dependency;
this module is the dependency-free equivalent: each engine is a plain
process holding a persistent namespace and listening on a localhost
socket; the :class:`Client` broadcasts code to every engine and gathers
results — the ``%%px`` execution model without the broker.

Engines receive the same ``BLUEFOG_TPU_*`` wiring as ``bfrun`` children
(see ``interactive_run.engine_env``), so ``import bluefog_tpu as bf;
bf.init()`` executed through the client forms a real multi-process
``jax.distributed`` job.  Because the client SENDS to every engine
before READING any reply, collective operations work: all engines enter
the collective concurrently.

Transport is length-prefixed pickle over 127.0.0.1 sockets.  Every
connection must authenticate with the cluster's random token (generated
at ``ibfrun start``, stored in the profile state file and handed to the
engines through their environment) before any exec/eval is accepted —
without it, any local user could connect to the port and run code as
the engine owner.  The handshake is a fixed-length RAW-BYTES HMAC
challenge/response (engine sends a random nonce, client returns
``HMAC-SHA256(token, nonce)``, compared with ``hmac.compare_digest``):
no pickle is deserialized until after auth succeeds, so an unauthorized
peer can never reach ``pickle.loads`` with attacker bytes, and the
token itself never crosses the socket.  This mirrors ipyparallel's
signed-message model at the granularity a local dev tool needs; still:
do not expose the ports beyond localhost.
"""

from __future__ import annotations

import hashlib
import hmac
import os
import pickle
import socket
import struct
import sys
import traceback
from typing import Any, List, Optional

from bluefog_tpu import config as bfconfig

__all__ = ["Client", "engine_main"]

_LEN = struct.Struct(">Q")


def _send(sock: socket.socket, obj: Any) -> None:
    data = pickle.dumps(obj)
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise EOFError("engine connection closed")
        buf += chunk
    return bytes(buf)


def _recv(sock: socket.socket) -> Any:
    header = _recv_exact(sock, _LEN.size)
    n = _LEN.unpack(header)[0]
    return pickle.loads(_recv_exact(sock, n))


_NONCE_LEN = 32
_MAC_LEN = hashlib.sha256().digest_size


def _auth_mac(token: str, nonce: bytes) -> bytes:
    return hmac.new(token.encode(), nonce, hashlib.sha256).digest()


def engine_main(port_file: str) -> None:
    """Engine process entry: listen on an ephemeral localhost port
    (announced atomically through ``port_file``), then serve exec/eval
    requests against one persistent namespace until shutdown.  Every
    connection must authenticate first (``BLUEFOG_TPU_ENGINE_TOKEN``)."""
    token = bfconfig.engine_token()
    ns: dict = {"__name__": "__bluefog_engine__"}
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    with open(port_file + ".tmp", "w") as f:
        f.write(str(port))
    os.replace(port_file + ".tmp", port_file)
    while True:
        conn, _ = srv.accept()
        try:
            # Fixed-length raw-bytes challenge/response BEFORE any
            # pickle touches the wire: an unauthenticated peer must
            # never reach pickle.loads (arbitrary-code gadget).
            nonce = os.urandom(_NONCE_LEN)
            conn.sendall(nonce)
            mac = _recv_exact(conn, _MAC_LEN)
            if not hmac.compare_digest(mac, _auth_mac(token, nonce)):
                conn.sendall(b"\x00")
                conn.close()
                continue
            conn.sendall(b"\x01")
            while True:
                msg = _recv(conn)
                op = msg.get("op")
                if op == "shutdown":
                    _send(conn, {"ok": True})
                    conn.close()
                    os._exit(0)
                try:
                    if op == "exec":
                        exec(msg["code"], ns)
                        _send(conn, {"ok": True})
                    elif op == "eval":
                        _send(conn, {"ok": True,
                                     "value": eval(msg["expr"], ns)})
                    else:
                        _send(conn, {"ok": False,
                                     "error": f"unknown op {op!r}"})
                except Exception:
                    _send(conn, {"ok": False,
                                 "error": traceback.format_exc()})
        except (EOFError, OSError):
            # client went away (clean close OR reset/broken pipe with
            # data in flight — e.g. a killed notebook kernel); await a
            # new connection rather than dying with the job state
            try:
                conn.close()
            except OSError:
                pass


class EngineError(RuntimeError):
    pass


class Client:
    """Drive a running native-engine cluster (``ibfrun start``).

    ``Client(profile).execute("import bluefog_tpu as bf; bf.init()")``
    runs on every engine concurrently; :meth:`eval` gathers per-engine
    values (which must be picklable — fetch numpy, not jax.Array).
    """

    def __init__(self, profile: str = "bluefog",
                 ports: Optional[List[int]] = None,
                 token: Optional[str] = None):
        if ports is None or token is None:
            from bluefog_tpu.run.interactive_run import load_state

            state = load_state(profile)
            if state is None or "engine_ports" not in state:
                raise FileNotFoundError(
                    f"no native engine cluster for profile '{profile}' — "
                    "start one with: ibfrun start -np N")
            ports = ports if ports is not None else state["engine_ports"]
            token = token if token is not None else state.get("token", "")
        self._socks = []
        try:
            for port in ports:
                s = socket.create_connection(("127.0.0.1", port),
                                             timeout=60)
                # the connect timeout must not persist per-operation: a
                # cell running longer than it would raise mid-protocol
                # and desynchronize the request/reply stream
                s.settimeout(None)
                self._socks.append(s)
                nonce = _recv_exact(s, _NONCE_LEN)
                s.sendall(_auth_mac(token, nonce))
                status = _recv_exact(s, 1)
                if status != b"\x01":
                    raise EngineError(
                        f"engine on port {port} rejected the client: "
                        "bad auth token")
        except BaseException:
            self.close()
            raise

    def __len__(self):
        return len(self._socks)

    def _broadcast(self, msg: dict) -> List[dict]:
        # send-to-all BEFORE read-any: engines may be entering a
        # collective that only completes once every engine runs it
        for s in self._socks:
            _send(s, msg)
        return [_recv(s) for s in self._socks]

    def _raise_on_error(self, replies: List[dict], what: str):
        errors = [(i, r["error"]) for i, r in enumerate(replies)
                  if not r.get("ok")]
        if errors:
            detail = "\n".join(f"--- engine {i} ---\n{e}"
                               for i, e in errors)
            raise EngineError(f"{what} failed on "
                              f"{len(errors)}/{len(replies)} engines:\n"
                              f"{detail}")

    def execute(self, code: str) -> None:
        """Run ``code`` on every engine (persistent namespace)."""
        self._raise_on_error(self._broadcast({"op": "exec", "code": code}),
                             f"execute({code!r})")

    def eval(self, expr: str) -> List[Any]:
        """Evaluate ``expr`` on every engine; returns per-engine values."""
        replies = self._broadcast({"op": "eval", "expr": expr})
        self._raise_on_error(replies, f"eval({expr!r})")
        return [r["value"] for r in replies]

    def shutdown(self) -> None:
        """Terminate every engine process (best-effort per engine: one
        dead engine must not keep the others running)."""
        for s in self._socks:
            try:
                _send(s, {"op": "shutdown"})
            except OSError:
                pass
        for s in self._socks:
            try:
                _recv(s)
            except (OSError, EOFError):
                pass
        self.close()

    def close(self) -> None:
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
        self._socks = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


if __name__ == "__main__":
    engine_main(sys.argv[1])
