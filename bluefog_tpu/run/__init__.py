"""bfrun launcher package (reference bluefog/run/)."""
