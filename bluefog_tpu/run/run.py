"""``bfrun`` — multi-process launcher for BlueFog-TPU jobs.

The reference's ``bfrun`` wraps ``mpirun`` with ssh reachability checks and
NIC discovery (reference bluefog/run/run.py:121-203).  On TPU none of that
exists: pods are launched by the platform (one process per host) and
``jax.distributed`` rendezvouses through a coordinator address.  This
launcher covers the two launch shapes:

* **Local multi-process** (default): spawn ``-np`` processes on this host,
  each a ``jax.distributed`` member.  With ``--force-cpu-devices K`` each
  process simulates K CPU devices — the single-host stand-in for a pod,
  used by the multi-process test suite (SURVEY.md §4).
* **Multi-host**: run the same ``bfrun`` command on every host with
  ``--host-rank R --coordinator HOST0:PORT`` (or let the TPU platform's
  launcher set the env) — no ssh orchestration needed, matching how TPU
  pods actually start jobs.

Child processes receive ``BLUEFOG_TPU_{COORDINATOR,NUM_PROCESSES,
PROCESS_ID}``; ``bluefog_tpu.init()`` picks these up and calls
``jax.distributed.initialize`` before touching the backend.

Env passthrough mirrors the reference's whitelist behavior
(reference run.py:180-203): BLUEFOG_*, JAX_*, XLA_* and the usual PATH/
PYTHON* variables are forwarded.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

PASS_PREFIXES = ("BLUEFOG_", "JAX_", "XLA_", "TPU_", "PYTHON", "PATH",
                 "HOME", "LD_", "TMPDIR", "VIRTUAL_ENV")


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="bfrun",
        usage="bfrun [options] <command> [args...]",
        description="Launch a BlueFog-TPU job (reference bfrun, run.py:58-118).")
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, default=1,
                        help="total number of processes")
    parser.add_argument("--coordinator", default="127.0.0.1:7675",
                        help="jax.distributed coordinator address host:port")
    parser.add_argument("--host-rank", type=int, default=0,
                        help="this host's index when launching multi-host "
                             "by hand (process ids are offset by "
                             "host_rank * procs_per_host)")
    parser.add_argument("--procs-per-host", type=int, default=None,
                        help="processes started on THIS host "
                             "(default: num-proc, i.e. single-host)")
    parser.add_argument("--force-cpu-devices", type=int, default=None,
                        metavar="K",
                        help="simulate K CPU devices per process "
                             "(testing; sets XLA_FLAGS + JAX_PLATFORMS)")
    parser.add_argument("--timeline-filename", default=None,
                        help="enable the timeline and write per-rank trace "
                             "files with this prefix (reference "
                             "run.py:106)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="elastic recovery (single-host launches "
                        "only): if a rank dies, tear the job down and "
                        "relaunch it up to this many times (training "
                        "scripts resume from their checkpoint; children "
                        "see BLUEFOG_TPU_RESTART_ATTEMPT, and each "
                        "attempt gets the next bindable coordinator "
                        "port).  Multi-host restart needs a supervisor "
                        "that coordinates every host's epoch — rejected "
                        "here rather than half-working.  The reference "
                        "has no restart story — its watchdog only names "
                        "stalled ranks")
    parser.add_argument("--extra-env", action="append", default=[],
                        metavar="K=V", help="extra env for the children")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the program to run")
    return parser


def _coordinator_for_attempt(coordinator: str, attempt: int) -> str:
    """Fresh port per restart attempt: the previous epoch's coordinator
    socket may linger in TIME_WAIT after a crash teardown.  Candidates
    are probed for bindability starting at base+attempt so a port owned
    by another process (e.g. a second job's live coordinator) is skipped
    instead of burning the restart budget.  Ports stay NEAR the base —
    an OS-assigned ephemeral port must not be used here, because between
    this probe and the child's bind it can be claimed as the SOURCE port
    of any outgoing connection on the host (observed: the restarted
    epoch's clients then hang in connect forever).  Single-host only
    (the parent picks the port and every child inherits it through the
    env), which is the scope --restarts is restricted to."""
    if attempt == 0:
        return coordinator
    import socket

    host, _, port = coordinator.rpartition(":")
    lo = min(int(port) + attempt, 65535)
    for candidate in range(lo, min(lo + 100, 65536)):
        try:
            with socket.socket() as s:
                s.bind((host or "127.0.0.1", candidate))
            return f"{host}:{candidate}"
        except OSError:
            continue
    raise RuntimeError(
        f"no bindable coordinator port within 100 of {port}")


def _child_env(args, process_id: int, attempt: int,
               coordinator: str) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k.startswith(PASS_PREFIXES)}
    # the caller resolves the coordinator ONCE per attempt (per-child
    # probing could hand ranks different addresses once rank 0's
    # service binds the first candidate)
    env["BLUEFOG_TPU_COORDINATOR"] = coordinator
    env["BLUEFOG_TPU_NUM_PROCESSES"] = str(args.num_proc)
    env["BLUEFOG_TPU_PROCESS_ID"] = str(process_id)
    env["BLUEFOG_TPU_RESTART_ATTEMPT"] = str(attempt)
    if args.force_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{args.force_cpu_devices}")
    if args.timeline_filename:
        env["BLUEFOG_TIMELINE"] = args.timeline_filename
    for kv in args.extra_env:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


# Child-output markers for the coordinator losing the bind race: the
# probed port can be claimed between the parent's probe and the child's
# bind (TOCTOU) — such an epoch is retried on the next candidate port
# without consuming the --restarts budget.  A marker line must also
# name the coordinator port, so a training script's OWN port collision
# (metrics server etc.) cannot masquerade as the coordinator race.
_BIND_FAILURE_MARKERS = ("Address already in use", "EADDRINUSE",
                         "Failed to bind")


def _stream(proc: subprocess.Popen, rank: int, coordinator: str,
            bind_failed: threading.Event):
    port = coordinator.rpartition(":")[2]
    for line in proc.stdout:
        if any(m in line for m in _BIND_FAILURE_MARKERS) \
                and (coordinator in line or f":{port}" in line):
            bind_failed.set()
        sys.stdout.write(f"[{rank}]<stdout> {line}")
        sys.stdout.flush()


def _run_once(args, command, base_id: int, procs_per_host: int,
              attempt: int, port_bump: int = 0):
    """Returns ``(exit_code, bind_failed)``; exit_code is None for
    KeyboardInterrupt (a sentinel distinct from any child-reachable
    code — never restarted).  ``bind_failed`` reports whether any child
    hit a coordinator bind failure (the probe-to-bind TOCTOU race)."""
    children = []
    threads = []
    bind_failed = threading.Event()

    def _terminate_all(sig=signal.SIGTERM):
        for proc in children:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass

    coordinator = _coordinator_for_attempt(args.coordinator,
                                           attempt + port_bump)
    try:
        for i in range(procs_per_host):
            env = _child_env(args, base_id + i, attempt, coordinator)
            proc = subprocess.Popen(
                command, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            children.append(proc)
            t = threading.Thread(
                target=_stream,
                args=(proc, base_id + i, coordinator, bind_failed),
                daemon=True)
            t.start()
            threads.append(t)
        # One failed rank must bring the job down (the others may be
        # blocked in collective rendezvous waiting for it forever).
        rc = 0
        alive = list(children)
        while alive:
            for proc in list(alive):
                code = proc.poll()
                if code is None:
                    continue
                alive.remove(proc)
                if code != 0:
                    rc = rc or code
                    sys.stderr.write(
                        f"bfrun: rank {children.index(proc) + base_id} "
                        f"exited with {code}; terminating the job\n")
                    _terminate_all()
            if alive:
                time.sleep(0.1)
        for t in threads:
            t.join(timeout=5)
        return rc, bind_failed.is_set()
    except KeyboardInterrupt:
        _terminate_all(signal.SIGINT)
        for proc in children:
            proc.wait()
        # sentinel distinct from any child exit code (a child exiting
        # 130 must still be eligible for --restarts)
        return None, False
    except Exception:
        _terminate_all()
        raise


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.version:
        from bluefog_tpu.version import __version__
        print(f"bfrun (bluefog_tpu) {__version__}")
        return 0
    if not args.command:
        make_parser().print_usage()
        return 2

    command = args.command
    if command and command[0] == "--":
        command = command[1:]
    procs_per_host = args.procs_per_host or args.num_proc
    base_id = args.host_rank * procs_per_host
    if base_id + procs_per_host > args.num_proc:
        sys.stderr.write("bfrun: host-rank/procs-per-host exceed -np\n")
        return 2
    if args.restarts and procs_per_host != args.num_proc:
        # A remote rank's death is invisible to this host's monitor (its
        # local children just block in rendezvous), and a restarted host
        # would rendezvous on a port the surviving hosts never learn —
        # refuse rather than hang half a pod.
        sys.stderr.write(
            "bfrun: --restarts only supports single-host launches "
            "(multi-host elastic restart needs a cross-host supervisor)\n")
        return 2

    attempt = 0
    port_bump = 0
    while True:
        rc, bind_failed = _run_once(args, command, base_id,
                                    procs_per_host, attempt, port_bump)
        if rc is None:  # KeyboardInterrupt: never restart
            return 130
        if rc != 0 and bind_failed and args.restarts and port_bump < 5:
            # probe-to-bind TOCTOU: another process claimed the probed
            # coordinator port first.  The epoch never really started —
            # move to the next candidate port without charging the
            # elastic-restart budget.
            port_bump += 1
            sys.stderr.write(
                "bfrun: coordinator lost the port bind race; retrying "
                f"on the next candidate (+{port_bump})\n")
            time.sleep(0.5)
            continue
        if rc == 0 or attempt >= args.restarts:
            return rc
        attempt += 1
        sys.stderr.write(
            f"bfrun: job failed (rc {rc}); elastic restart "
            f"{attempt}/{args.restarts} — children resume from their "
            "checkpoints\n")
        time.sleep(1.0)


if __name__ == "__main__":
    sys.exit(main())
