"""``bfrun`` — multi-process launcher for BlueFog-TPU jobs.

The reference's ``bfrun`` wraps ``mpirun`` with ssh reachability checks and
NIC discovery (reference bluefog/run/run.py:121-203).  On TPU none of that
exists: pods are launched by the platform (one process per host) and
``jax.distributed`` rendezvouses through a coordinator address.  This
launcher covers the two launch shapes:

* **Local multi-process** (default): spawn ``-np`` processes on this host,
  each a ``jax.distributed`` member.  With ``--force-cpu-devices K`` each
  process simulates K CPU devices — the single-host stand-in for a pod,
  used by the multi-process test suite (SURVEY.md §4).
* **Multi-host, by hand**: run the same ``bfrun`` command on every host
  with ``--host-rank R --coordinator HOST0:PORT`` (or let the TPU
  platform's launcher set the env), matching how TPU pods start jobs.
* **Multi-host, one command** (``-H host1:2,host2:2``): this ``bfrun``
  ssh-checks every host, then spawns one remote ``bfrun`` per host over
  ssh (cwd + whitelisted env propagated on the remote command line,
  rank offsets from the slot list, coordinator defaulting to the first
  host) and fail-fast tears the whole job down when any host's launcher
  exits nonzero — the reference's one-command pod launch
  (reference bluefog/run/run.py:121-203), re-based on ssh-fanout of the
  local spawner instead of a vendored mpirun driver.
  ``--launch-transport local`` swaps ssh for a local shell (host names
  become labels) so the full orchestration path is testable — and
  usable — without sshd.

Child processes receive ``BLUEFOG_TPU_{COORDINATOR,NUM_PROCESSES,
PROCESS_ID}``; ``bluefog_tpu.init()`` picks these up and calls
``jax.distributed.initialize`` before touching the backend.

Env passthrough mirrors the reference's whitelist behavior
(reference run.py:180-203): BLUEFOG_*, JAX_*, XLA_* and the usual PATH/
PYTHON* variables are forwarded.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import threading
import time

from bluefog_tpu import config as bfconfig

PASS_PREFIXES = ("BLUEFOG_", "JAX_", "XLA_", "TPU_", "PYTHON", "PATH",
                 "HOME", "LD_", "TMPDIR", "VIRTUAL_ENV")


def make_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="bfrun",
        usage="bfrun [options] <command> [args...]",
        description="Launch a BlueFog-TPU job (reference bfrun, run.py:58-118).")
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, default=1,
                        help="total number of processes")
    parser.add_argument("--coordinator", default="127.0.0.1:7675",
                        help="jax.distributed coordinator address host:port")
    parser.add_argument("--host-rank", type=int, default=0,
                        help="this host's index when launching multi-host "
                             "by hand (process ids are offset by "
                             "host_rank * procs_per_host)")
    parser.add_argument("--procs-per-host", type=int, default=None,
                        help="processes started on THIS host "
                             "(default: num-proc, i.e. single-host)")
    parser.add_argument("--force-cpu-devices", type=int, default=None,
                        metavar="K",
                        help="simulate K CPU devices per process "
                             "(testing; sets XLA_FLAGS + JAX_PLATFORMS)")
    parser.add_argument("--timeline-filename", default=None,
                        help="enable the timeline and write per-rank trace "
                             "files with this prefix (reference "
                             "run.py:106)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="elastic recovery (single-host launches "
                        "only): if a rank dies, tear the job down and "
                        "relaunch it up to this many times (training "
                        "scripts resume from their checkpoint; children "
                        "see BLUEFOG_TPU_RESTART_ATTEMPT, and each "
                        "attempt gets the next bindable coordinator "
                        "port).  Multi-host restart needs a supervisor "
                        "that coordinates every host's epoch — rejected "
                        "here rather than half-working.  The reference "
                        "has no restart story — its watchdog only names "
                        "stalled ranks")
    parser.add_argument("--extra-env", action="append", default=[],
                        metavar="K=V", help="extra env for the children")
    parser.add_argument("-H", "--hosts", default=None,
                        metavar="host1:slots,host2:slots",
                        help="one-command multi-host launch: spawn one "
                             "remote bfrun per host over ssh with rank "
                             "offsets from the slot list (total "
                             "processes = sum of slots; -np may be "
                             "omitted).  The coordinator defaults to "
                             "the FIRST host")
    parser.add_argument("--launch-transport", choices=("ssh", "local"),
                        default="ssh",
                        help="how -H reaches each host: 'ssh' (default) "
                             "or 'local' (spawn every host's launcher "
                             "on this machine — tests/sshd-less setups)")
    parser.add_argument("--no-ssh-check", action="store_true",
                        help="skip the pre-launch ssh reachability check")
    parser.add_argument("--rank-offset", type=int, default=None,
                        help=argparse.SUPPRESS)  # set by the -H parent:
    # first global process id on this host (overrides host_rank *
    # procs_per_host, which assumes uniform slots)
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the program to run")
    return parser


def parse_hosts(spec: str):
    """``host1:2,host2:2`` -> ``[("host1", 2), ("host2", 2)]`` (the
    reference's -H format, reference run_util.py hosts parsing)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        host, sep, slots = part.rpartition(":")
        if not host or not sep or not slots.isdigit() or int(slots) < 1:
            raise ValueError(
                f"bad -H entry {part!r}: expected host:slots with "
                "slots >= 1")
        out.append((host, int(slots)))
    if len({h for h, _ in out}) != len(out):
        raise ValueError(f"duplicate host in -H list: {spec!r}")
    return out


def _coordinator_for_attempt(coordinator: str, attempt: int) -> str:
    """Fresh port per restart attempt: the previous epoch's coordinator
    socket may linger in TIME_WAIT after a crash teardown.  Candidates
    are probed for bindability starting at base+attempt so a port owned
    by another process (e.g. a second job's live coordinator) is skipped
    instead of burning the restart budget.  Ports stay NEAR the base —
    an OS-assigned ephemeral port must not be used here, because between
    this probe and the child's bind it can be claimed as the SOURCE port
    of any outgoing connection on the host (observed: the restarted
    epoch's clients then hang in connect forever).  Single-host only
    (the parent picks the port and every child inherits it through the
    env), which is the scope --restarts is restricted to."""
    if attempt == 0:
        return coordinator
    import socket

    host, _, port = coordinator.rpartition(":")
    lo = min(int(port) + attempt, 65535)
    for candidate in range(lo, min(lo + 100, 65536)):
        try:
            with socket.socket() as s:
                s.bind((host or "127.0.0.1", candidate))
            return f"{host}:{candidate}"
        except OSError:
            continue
    raise RuntimeError(
        f"no bindable coordinator port within 100 of {port}")


def _child_env(args, process_id: int, attempt: int,
               coordinator: str) -> dict:
    env = {k: v for k, v in bfconfig.environ_passthrough().items()
           if k.startswith(PASS_PREFIXES)}
    # the caller resolves the coordinator ONCE per attempt (per-child
    # probing could hand ranks different addresses once rank 0's
    # service binds the first candidate)
    env["BLUEFOG_TPU_COORDINATOR"] = coordinator
    env["BLUEFOG_TPU_NUM_PROCESSES"] = str(args.num_proc)
    env["BLUEFOG_TPU_PROCESS_ID"] = str(process_id)
    env["BLUEFOG_TPU_RESTART_ATTEMPT"] = str(attempt)
    if args.force_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{args.force_cpu_devices}")
    if args.timeline_filename:
        env["BLUEFOG_TIMELINE"] = args.timeline_filename
    for kv in args.extra_env:
        k, _, v = kv.partition("=")
        env[k] = v
    return env


# Child-output markers for the coordinator losing the bind race: the
# probed port can be claimed between the parent's probe and the child's
# bind (TOCTOU) — such an epoch is retried on the next candidate port
# without consuming the --restarts budget.  A marker line must also
# name the coordinator port, so a training script's OWN port collision
# (metrics server etc.) cannot masquerade as the coordinator race.
_BIND_FAILURE_MARKERS = ("Address already in use", "EADDRINUSE",
                         "Failed to bind")


def _stream(proc: subprocess.Popen, rank: int, coordinator: str,
            bind_failed: threading.Event):
    port = coordinator.rpartition(":")[2]
    for line in proc.stdout:
        if any(m in line for m in _BIND_FAILURE_MARKERS) \
                and (coordinator in line or f":{port}" in line):
            bind_failed.set()
        sys.stdout.write(f"[{rank}]<stdout> {line}")
        sys.stdout.flush()


_TERMINATE_GRACE_S = 5.0


def _supervise(children, describe, terminate_all) -> int:
    """The shared fail-fast poll loop: wait for every child, and on the
    FIRST nonzero exit report it (``describe(index, code)``) and tear
    the rest down — the others may be blocked in collective rendezvous
    waiting for the dead one forever.  A child that ignores SIGTERM
    (e.g. an ssh client hung on a dead connection in the ``-H`` path)
    is SIGKILLed after a grace period so teardown cannot block
    indefinitely.  Returns the first nonzero exit code (or 0)."""
    rc = 0
    term_deadline = None
    alive = list(children)
    while alive:
        for proc in list(alive):
            code = proc.poll()
            if code is None:
                continue
            alive.remove(proc)
            if code != 0 and rc == 0:
                rc = code
                sys.stderr.write(describe(children.index(proc), code))
                terminate_all()
                term_deadline = time.monotonic() + _TERMINATE_GRACE_S
        if alive:
            if term_deadline is not None \
                    and time.monotonic() > term_deadline:
                terminate_all(signal.SIGKILL)
                term_deadline = float("inf")  # escalate once
            time.sleep(0.1)
    return rc


def _run_once(args, command, base_id: int, procs_per_host: int,
              attempt: int, port_bump: int = 0):
    """Returns ``(exit_code, bind_failed)``; exit_code is None for
    KeyboardInterrupt (a sentinel distinct from any child-reachable
    code — never restarted).  ``bind_failed`` reports whether any child
    hit a coordinator bind failure (the probe-to-bind TOCTOU race)."""
    children = []
    threads = []
    bind_failed = threading.Event()

    def _terminate_all(sig=signal.SIGTERM):
        for proc in children:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass

    coordinator = _coordinator_for_attempt(args.coordinator,
                                           attempt + port_bump)
    try:
        for i in range(procs_per_host):
            env = _child_env(args, base_id + i, attempt, coordinator)
            proc = subprocess.Popen(
                command, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True)
            children.append(proc)
            t = threading.Thread(
                target=_stream,
                args=(proc, base_id + i, coordinator, bind_failed),
                daemon=True)
            t.start()
            threads.append(t)
        rc = _supervise(
            children,
            lambda i, code: (f"bfrun: rank {i + base_id} exited with "
                             f"{code}; terminating the job\n"),
            _terminate_all)
        for t in threads:
            t.join(timeout=5)
        return rc, bind_failed.is_set()
    except KeyboardInterrupt:
        _terminate_all(signal.SIGINT)
        for proc in children:
            proc.wait()
        # sentinel distinct from any child exit code (a child exiting
        # 130 must still be eligible for --restarts)
        return None, False
    except Exception:
        _terminate_all()
        raise


def _ssh_argv(host: str, tty: bool = False):
    # BatchMode: fail fast instead of prompting for a password inside a
    # launcher (the reference's ssh checks are likewise non-interactive).
    # tty (-tt): launches run on a forced pty so the REMOTE side is
    # SIGHUP'd when this client dies or is killed — without it, killing
    # the local ssh process orphans every remote rank (non-pty sessions
    # get no hangup; the remote bfrun's SIGHUP->teardown handler in
    # main() would never fire).
    argv = ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=10"]
    if tty:
        argv.append("-tt")
    return argv + [host]


def check_ssh_reachability(hosts, timeout: float = 20.0):
    """Probe every host with a no-op ssh command IN PARALLEL and raise
    one error naming ALL unreachable hosts (reference run.py's
    _check_all_hosts_ssh_successful behavior: fail before launching
    anything anywhere)."""
    procs = {h: subprocess.Popen(
        _ssh_argv(h) + ["true"], stdout=subprocess.DEVNULL,
        stderr=subprocess.PIPE, text=True) for h, _ in hosts}
    failed = []
    deadline = time.time() + timeout
    for host, proc in procs.items():
        try:
            rc = proc.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            proc.kill()
            failed.append(f"{host} (timeout)")
            continue
        if rc != 0:
            err = (proc.stderr.read() or "").strip().splitlines()
            failed.append(f"{host} ({err[-1] if err else f'rc {rc}'})")
    if failed:
        raise RuntimeError(
            "bfrun: ssh unreachable: " + "; ".join(failed)
            + ". Every host must accept passwordless ssh (BatchMode), "
            "or use --launch-transport local / --no-ssh-check.")


def _host_launcher_argv(args, host: str, host_rank: int, offset: int,
                        slots: int, total: int, coordinator: str,
                        command) -> list:
    """The per-host process: a remote (or local) bfrun covering this
    host's slot range.  cwd + the whitelisted env ride the command line
    (`cd ... && env K=V ... python -m bluefog_tpu.run ...`), so the
    remote side needs nothing but the repo at the same path."""
    import shlex

    inner = [sys.executable, "-m", "bluefog_tpu.run",
             "-np", str(total), "--coordinator", coordinator,
             "--host-rank", str(host_rank),
             "--procs-per-host", str(slots),
             "--rank-offset", str(offset)]
    if args.force_cpu_devices:
        inner += ["--force-cpu-devices", str(args.force_cpu_devices)]
    if args.timeline_filename:
        inner += ["--timeline-filename", args.timeline_filename]
    for kv in args.extra_env:
        inner += ["--extra-env", kv]
    inner += ["--"] + list(command)
    env_pairs = [f"{k}={v}"
                 for k, v in sorted(bfconfig.environ_passthrough().items())
                 if k.startswith(PASS_PREFIXES)]
    shell = ("cd " + shlex.quote(os.getcwd()) + " && exec env "
             + " ".join(shlex.quote(p) for p in env_pairs) + " "
             + " ".join(shlex.quote(t) for t in inner))
    if args.launch_transport == "local":
        return ["bash", "-c", shell]
    return _ssh_argv(host, tty=True) + [shell]


def _run_multihost(args, command) -> int:
    try:
        hosts = parse_hosts(args.hosts)
    except ValueError as e:
        sys.stderr.write(f"bfrun: {e}\n")
        return 2
    total = sum(s for _, s in hosts)
    if args.num_proc not in (1, total):
        sys.stderr.write(
            f"bfrun: -np {args.num_proc} does not match the -H slot "
            f"total {total} (omit -np with -H)\n")
        return 2
    if args.restarts:
        sys.stderr.write(
            "bfrun: --restarts only supports single-host launches "
            "(multi-host elastic restart needs a cross-host "
            "supervisor)\n")
        return 2
    coordinator = args.coordinator
    if args.launch_transport == "ssh" and \
            coordinator.startswith("127.0.0.1:"):
        # the default loopback coordinator is meaningless across hosts:
        # rendezvous on the first host — minus any ssh login name
        # (-H user@host:2 is the common mpirun-style spec, but
        # 'user@host' is not a resolvable rendezvous address)
        first = hosts[0][0].rpartition("@")[2]
        coordinator = first + ":" + coordinator.rpartition(":")[2]
    if args.launch_transport == "ssh" and not args.no_ssh_check:
        try:
            check_ssh_reachability(hosts)
        except RuntimeError as e:
            sys.stderr.write(str(e) + "\n")
            return 2

    children, threads = [], []

    def _terminate_all(sig=signal.SIGTERM):
        for proc in children:
            if proc.poll() is None:
                try:
                    proc.send_signal(sig)
                except OSError:
                    pass

    def _stream_host(proc, host):
        for line in proc.stdout:
            sys.stdout.write(f"[{host}] {line}")
            sys.stdout.flush()

    offset = 0
    try:
        for i, (host, slots) in enumerate(hosts):
            argv = _host_launcher_argv(args, host, i, offset, slots,
                                       total, coordinator, command)
            proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                    stderr=subprocess.STDOUT, text=True)
            children.append(proc)
            t = threading.Thread(target=_stream_host, args=(proc, host),
                                 daemon=True)
            t.start()
            threads.append(t)
            offset += slots
        # a host's launcher exiting nonzero already tore down its own
        # local ranks; take the other hosts with it
        rc = _supervise(
            children,
            lambda i, code: (f"bfrun: host {hosts[i][0]} exited with "
                             f"{code}; tearing down the remaining "
                             "hosts\n"),
            _terminate_all)
        for t in threads:
            t.join(timeout=5)
        return rc
    except KeyboardInterrupt:
        _terminate_all(signal.SIGINT)
        for proc in children:
            proc.wait()
        return 130
    except Exception:
        _terminate_all()
        raise


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.version:
        from bluefog_tpu.version import __version__
        print(f"bfrun (bluefog_tpu) {__version__}")
        return 0
    if not args.command:
        make_parser().print_usage()
        return 2

    command = args.command
    if command and command[0] == "--":
        command = command[1:]

    # a dropped controlling connection (ssh teardown from a multi-host
    # parent) or a TERM must take the local ranks down with us, exactly
    # like Ctrl-C
    def _teardown_signal(signum, frame):
        raise KeyboardInterrupt

    for _sig in (signal.SIGTERM, signal.SIGHUP):
        try:
            signal.signal(_sig, _teardown_signal)
        except (ValueError, OSError):  # non-main thread / platform quirk
            pass

    if args.hosts:
        return _run_multihost(args, command)

    procs_per_host = args.procs_per_host or args.num_proc
    base_id = args.rank_offset if args.rank_offset is not None \
        else args.host_rank * procs_per_host
    if base_id + procs_per_host > args.num_proc:
        sys.stderr.write("bfrun: host-rank/procs-per-host exceed -np\n")
        return 2
    if args.restarts and procs_per_host != args.num_proc:
        # A remote rank's death is invisible to this host's monitor (its
        # local children just block in rendezvous), and a restarted host
        # would rendezvous on a port the surviving hosts never learn —
        # refuse rather than hang half a pod.
        sys.stderr.write(
            "bfrun: --restarts only supports single-host launches "
            "(multi-host elastic restart needs a cross-host supervisor)\n")
        return 2

    attempt = 0
    port_bump = 0
    while True:
        rc, bind_failed = _run_once(args, command, base_id,
                                    procs_per_host, attempt, port_bump)
        if rc is None:  # KeyboardInterrupt: never restart
            return 130
        if rc != 0 and bind_failed and args.restarts and port_bump < 5:
            # probe-to-bind TOCTOU: another process claimed the probed
            # coordinator port first.  The epoch never really started —
            # move to the next candidate port without charging the
            # elastic-restart budget.
            port_bump += 1
            sys.stderr.write(
                "bfrun: coordinator lost the port bind race; retrying "
                f"on the next candidate (+{port_bump})\n")
            time.sleep(0.5)
            continue
        if rc == 0 or attempt >= args.restarts:
            return rc
        attempt += 1
        sys.stderr.write(
            f"bfrun: job failed (rc {rc}); elastic restart "
            f"{attempt}/{args.restarts} — children resume from their "
            "checkpoints\n")
        time.sleep(1.0)


if __name__ == "__main__":
    sys.exit(main())
