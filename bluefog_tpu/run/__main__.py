"""``python -m bluefog_tpu.run`` == ``bfrun``."""

import sys

from bluefog_tpu.run.run import main

if __name__ == "__main__":
    sys.exit(main())
