"""``ibfrun`` — interactive (Jupyter) cluster launcher.

Reference parity: bluefog/run/interactive_run.py starts/stops an
ipyparallel cluster so notebook cells can drive a BlueFog job.  On TPU the
single-controller JAX model makes most notebook use direct (one process
sees all chips), so this exists for the multi-process case only and is
gated on ipyparallel being installed.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ibfrun", description="Interactive BlueFog-TPU cluster "
        "(reference interactive_run.py)")
    parser.add_argument("action", choices=["start", "stop"])
    parser.add_argument("-np", "--num-proc", type=int, default=1)
    parser.add_argument("--profile", default="bluefog")
    args = parser.parse_args(argv)

    try:
        import ipyparallel  # noqa: F401
    except ImportError:
        sys.stderr.write(
            "ibfrun requires ipyparallel, which is not installed.\n"
            "Single-process TPU notebooks do not need ibfrun: one process "
            "addresses every chip — just `import bluefog_tpu` and init().\n")
        return 1

    import subprocess
    if args.action == "start":
        cmd = ["ipcluster", "start", f"--profile={args.profile}",
               f"--n={args.num_proc}", "--daemonize"]
    else:
        cmd = ["ipcluster", "stop", f"--profile={args.profile}"]
    return subprocess.call(cmd)


if __name__ == "__main__":
    sys.exit(main())
