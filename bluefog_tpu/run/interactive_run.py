"""``ibfrun`` — interactive (Jupyter) cluster launcher.

Reference parity: bluefog/run/interactive_run.py starts an ipyparallel
controller plus engines *launched under mpirun* so every engine is an MPI
rank with bluefog initialized; notebook cells then drive the job with
``%%px``.  The TPU translation: engines are spawned directly (no mpirun),
each with the ``BLUEFOG_TPU_{COORDINATOR,NUM_PROCESSES,PROCESS_ID}``
environment that ``bluefog_tpu.init()`` turns into a
``jax.distributed.initialize`` — so executing ``import bluefog_tpu as
bf; bf.init()`` on the engines forms the same multi-process job a
``bfrun`` launch would.

Two backends:

* ``--backend native`` (default, no dependencies): the engines from
  ``bluefog_tpu.run.engines`` — persistent-namespace processes on
  localhost sockets, driven by ``engines.Client(profile)``
  (``client.execute(...)`` / ``client.eval(...)`` — the ``%%px``
  execution model without the broker).
* ``--backend ipyparallel``: the reference-style ipcontroller +
  ipengines for notebooks that want real ``%%px`` (requires
  ipyparallel).

State (engine pids/ports, coordinator address) is kept in
``~/.bluefog_tpu/ibfrun_<profile>.json`` (the reference keeps engine pids
in the ipython profile dir, interactive_run.py:170-195) so ``ibfrun stop``
can tear the cluster down even from a fresh shell.

Single-process TPU notebooks do not need any of this: one process
addresses every chip — just ``import bluefog_tpu`` and ``init()``.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from bluefog_tpu import config as bfconfig
from bluefog_tpu.run.run import PASS_PREFIXES


def _state_path(profile: str) -> str:
    d = bfconfig.state_dir()
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"ibfrun_{profile}.json")


def engine_env(process_id: int, num_proc: int, coordinator: str,
               force_cpu_devices: Optional[int] = None,
               base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Environment for engine ``process_id`` — the wiring that makes an
    ipengine a member of the bluefog_tpu job (the reference gets this from
    mpirun's rank assignment; here bfrun's env contract is reused,
    bluefog_tpu/run/run.py _child_env)."""
    env = {k: v for k, v in bfconfig.environ_passthrough(base_env).items()
           if k.startswith(PASS_PREFIXES)}
    env["BLUEFOG_TPU_COORDINATOR"] = coordinator
    env["BLUEFOG_TPU_NUM_PROCESSES"] = str(num_proc)
    env["BLUEFOG_TPU_PROCESS_ID"] = str(process_id)
    if force_cpu_devices:
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            f" --xla_force_host_platform_device_count="
                            f"{force_cpu_devices}")
    return env


def _reap(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()


def save_state(profile: str, controller_pid: int, engine_pids: List[int],
               coordinator: str, num_proc: int,
               engine_ports: Optional[List[int]] = None,
               token: Optional[str] = None) -> str:
    path = _state_path(profile)
    state = {"controller_pid": controller_pid,
             "engine_pids": engine_pids,
             "coordinator": coordinator,
             "num_proc": num_proc}
    if engine_ports is not None:
        state["engine_ports"] = engine_ports
    if token is not None:
        state["token"] = token
    with open(path, "w") as f:
        json.dump(state, f)
    # the state file now carries the auth token — owner-only
    os.chmod(path, 0o600)
    return path


def load_state(profile: str) -> Optional[dict]:
    path = _state_path(profile)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def clear_state(profile: str) -> None:
    path = _state_path(profile)
    if os.path.exists(path):
        os.remove(path)


def _kill(pid: int, sig=signal.SIGINT) -> bool:
    try:
        os.kill(pid, sig)
        return True
    except (OSError, ProcessLookupError):
        return False


def start_native_cluster(num_proc: int, profile: str, coordinator: str,
                         force_cpu_devices: Optional[int] = None,
                         engine_ready_timeout: float = 60.0) -> int:
    """Start ``num_proc`` native engines (bluefog_tpu.run.engines) —
    dependency-free; drive them with ``engines.Client(profile)``."""
    import secrets
    import shutil
    import tempfile

    token = secrets.token_hex(16)
    port_dir = tempfile.mkdtemp(prefix="ibfrun_ports_")
    engines = []
    try:
        port_files = []
        for i in range(num_proc):
            env = engine_env(i, num_proc, coordinator, force_cpu_devices)
            env["BLUEFOG_TPU_ENGINE_TOKEN"] = token
            pf = os.path.join(port_dir, f"engine{i}.port")
            port_files.append(pf)
            engines.append(subprocess.Popen(
                [sys.executable, "-m", "bluefog_tpu.run.engines", pf],
                env=env))
        deadline = time.time() + engine_ready_timeout
        ports = []
        for i, pf in enumerate(port_files):
            while not os.path.exists(pf):
                if time.time() > deadline:
                    sys.stderr.write(
                        f"ibfrun: engine {i} did not announce its port "
                        f"within {engine_ready_timeout}s\n")
                    raise TimeoutError
                if engines[i].poll() is not None:
                    sys.stderr.write(
                        f"ibfrun: engine {i} exited "
                        f"({engines[i].returncode}) during startup\n")
                    raise TimeoutError
                time.sleep(0.05)
            with open(pf) as f:
                ports.append(int(f.read().strip()))
    except TimeoutError:
        _reap(engines)
        return 1
    except BaseException:
        # ANY failed start (Popen OSError, Ctrl-C in the wait loop, ...)
        # must not orphan the engines that DID come up — they would
        # squat BLUEFOG_TPU_* rendezvous state with no cluster record
        # for 'ibfrun stop' to find
        _reap(engines)
        raise
    finally:
        shutil.rmtree(port_dir, ignore_errors=True)
    path = save_state(profile, 0, [p.pid for p in engines], coordinator,
                      num_proc, engine_ports=ports, token=token)
    print(f"ibfrun: started {num_proc} native engines; state in {path}")
    print("Drive them with:\n"
          "  from bluefog_tpu.run.engines import Client\n"
          f"  c = Client(profile={profile!r})\n"
          "  c.execute('import bluefog_tpu as bf; bf.init()')\n"
          "  c.eval('bf.rank()')")
    return 0


def start_cluster(num_proc: int, profile: str, coordinator: str,
                  force_cpu_devices: Optional[int] = None,
                  engine_ready_timeout: float = 60.0) -> int:
    """Start ipcontroller + num_proc wired ipengines.  Returns 0 on
    success.  Requires ipyparallel."""
    controller = subprocess.Popen(
        [sys.executable, "-m", "ipyparallel.controller",
         f"--profile={profile}"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    # the controller writes its connection files asynchronously; engines
    # retry on their own, so a short grace period suffices
    time.sleep(2.0)
    engines = []
    for i in range(num_proc):
        env = engine_env(i, num_proc, coordinator, force_cpu_devices)
        engines.append(subprocess.Popen(
            [sys.executable, "-m", "ipyparallel.engine",
             f"--profile={profile}"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
    path = save_state(profile, controller.pid, [p.pid for p in engines],
                      coordinator, num_proc)
    print(f"ibfrun: started controller (pid {controller.pid}) + "
          f"{num_proc} engines; state in {path}")
    print("In the notebook:\n"
          f"  import ipyparallel as ipp; rc = ipp.Client(profile={profile!r})\n"
          "  %%px\n"
          "  import bluefog_tpu as bf\n"
          "  bf.init()")
    return 0


def stop_cluster(profile: str) -> int:
    state = load_state(profile)
    if state is None:
        sys.stderr.write(f"ibfrun: no running cluster for profile "
                         f"'{profile}'\n")
        return 1
    sig = (signal.SIGTERM if state.get("engine_ports")  # native engines
           else signal.SIGINT)
    for pid in state["engine_pids"]:
        _kill(pid, sig)
    if state.get("controller_pid"):
        _kill(state["controller_pid"])
    clear_state(profile)
    print(f"ibfrun: stopped cluster '{profile}'")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="ibfrun", description="Interactive BlueFog-TPU cluster "
        "(reference interactive_run.py)")
    parser.add_argument("action", choices=["start", "stop"])
    parser.add_argument("-np", "--num-proc", type=int, default=1)
    parser.add_argument("--profile", default="bluefog")
    parser.add_argument("--backend", default="native",
                        choices=["native", "ipyparallel"],
                        help="native: dependency-free engines driven by "
                        "engines.Client; ipyparallel: reference-style "
                        "ipcontroller + %%px (requires ipyparallel)")
    parser.add_argument("--coordinator", default="127.0.0.1:7675",
                        help="jax.distributed coordinator address")
    parser.add_argument("--force-cpu-devices", type=int, default=None,
                        metavar="K",
                        help="simulate K CPU devices per engine (testing)")
    args = parser.parse_args(argv)

    if args.action == "stop":
        return stop_cluster(args.profile)
    if args.backend == "native":
        return start_native_cluster(args.num_proc, args.profile,
                                    args.coordinator,
                                    args.force_cpu_devices)
    try:
        import ipyparallel  # noqa: F401
    except ImportError:
        sys.stderr.write(
            "ibfrun --backend ipyparallel requires ipyparallel, which is "
            "not installed; the default --backend native has no "
            "dependencies.\nSingle-process TPU notebooks do not need "
            "ibfrun: one process addresses every chip — just `import "
            "bluefog_tpu` and init().\n")
        return 1
    return start_cluster(args.num_proc, args.profile, args.coordinator,
                         args.force_cpu_devices)


if __name__ == "__main__":
    sys.exit(main())
