"""Environment-variable configuration.

The reference configures everything through ``BLUEFOG_*`` env vars
(reference: docs/env_variable.rst; operations.cc:42-47).  We honor the same
names where they still mean something on TPU, and document the ones that are
obsolete by construction (fusion/cycle/negotiation are XLA's job now).
"""

from __future__ import annotations

import os

__all__ = [
    "log_level",
    "log_hide_time",
    "log_format",
    "observe",
    "observe_raw",
    "blackbox_enabled",
    "blackbox_capacity",
    "blackbox_dump_dir",
    "timeline_path",
    "timeline_flush_every",
    "timeline_queue_capacity",
    "timeline_native",
    "straggler_z_threshold",
    "skip_negotiate_default",
    "ops_on_cpu",
    "stall_warning_time",
    "op_timeout",
    "fuse_epilogues",
    "fusion_threshold",
    "hier_local_size",
    "mix_compress",
    "mix_compress_ratio",
    "moe_capacity_factor",
    "kv_zero_on_free",
    "prefix_cache_mb",
    "replica_stale_s",
    "router_retries",
    "router_retry_base_s",
    "router_cooldown_s",
    "elastic_bootstrap_rounds",
    "elastic_quarantine_threshold",
    "topology_replan_window",
    "topology_replan_patience",
    "topology_replan_degrade_ratio",
    "topology_replan_margin",
    "topology_replan_cooldown",
    "topology_replan_probation",
    "coordinator",
    "num_processes",
    "process_id",
    "engine_token",
    "state_dir",
    "chip_peak_tflops_override",
    "chip_hbm_gbps_override",
    "environ_passthrough",
    "configure_host_platform",
]


def _env(name: str, default: str = "") -> str:
    return os.environ.get(name, default)


def log_level() -> str:
    """BLUEFOG_LOG_LEVEL: trace|debug|info|warn|error|fatal (reference
    logging.h:75, docs/env_variable.rst:9-16)."""
    return _env("BLUEFOG_LOG_LEVEL", "warn").lower()


def log_hide_time() -> bool:
    """BLUEFOG_LOG_HIDE_TIME (reference logging.h:76)."""
    return _env("BLUEFOG_LOG_HIDE_TIME", "0") in ("1", "true", "True")


def log_format() -> str:
    """BLUEFOG_LOG_FORMAT: ``text`` (default, human-readable) or
    ``json`` — one JSON object per line with rank/timestamp/level, the
    shape log aggregators ingest without a parse rule."""
    return _env("BLUEFOG_LOG_FORMAT", "text").lower()


def observe() -> bool:
    """BLUEFOG_OBSERVE (default on): whether the built-in publishers
    write into the observability registry/tracer
    (:mod:`bluefog_tpu.observe`).  ``0`` opts out."""
    return observe_raw()


def observe_raw() -> bool:
    """The raw BLUEFOG_OBSERVE read.
    :func:`bluefog_tpu.observe.registry.enabled` is the public gate the
    publishers call; it delegates here so the env access itself lives in
    this module (the ``env-read-outside-config`` lint contract)."""
    return _env("BLUEFOG_OBSERVE", "1") not in ("0", "false", "False")


def blackbox_enabled() -> bool:
    """BLUEFOG_BLACKBOX (default on): whether the control planes record
    into the process-global decision flight recorder
    (:mod:`bluefog_tpu.observe.blackbox`).  ``0`` opts out; compiled
    programs and step outputs are bit-identical either way — the
    recorder is host-side only, like BLUEFOG_OBSERVE."""
    return _env("BLUEFOG_BLACKBOX", "1") not in ("0", "false", "False")


def blackbox_capacity() -> int:
    """BLUEFOG_BLACKBOX_CAPACITY (default 4096): bound of the decision
    flight recorder's event ring.  At capacity the oldest event is
    evicted and counted (``bf_blackbox_dropped_events``) — O(1) memory
    however long the run; the streaming chain digest is unaffected by
    eviction."""
    try:
        return max(1, int(_env("BLUEFOG_BLACKBOX_CAPACITY", "4096")))
    except ValueError:
        return 4096


def blackbox_dump_dir() -> str:
    """BLUEFOG_BLACKBOX_DUMP: directory the recorder dumps its ring
    into (one JSONL file per anomaly kind) when an anomaly — rollback,
    ``rank_join_failed``, lost request, bench-gate failure — is
    recorded.  Empty (the default) disables the file dump; the
    Chrome-trace instant and the drop/decision counters publish either
    way."""
    return _env("BLUEFOG_BLACKBOX_DUMP", "")


def timeline_path() -> str:
    """BLUEFOG_TIMELINE: path prefix for per-process Chrome-trace files
    (reference operations.cc:464-473)."""
    return _env("BLUEFOG_TIMELINE", "")


def timeline_flush_every() -> int:
    """BLUEFOG_TIMELINE_FLUSH_EVERY (default 1024): every this many
    events drained by the Python timeline writer, the accumulated drop
    count flushes to the ``bf_timeline_dropped_events`` gauge — a
    long-running saturated run is visible before shutdown, not only at
    ``close()``."""
    try:
        return max(1, int(_env("BLUEFOG_TIMELINE_FLUSH_EVERY", "1024")))
    except ValueError:
        return 1024


def timeline_queue_capacity() -> int:
    """BLUEFOG_TIMELINE_QUEUE_CAPACITY (default 65536): bound of the
    Python timeline writer's event queue — roughly the native ring's
    depth.  A full queue drops the event and counts it (the bounded
    contract both backends share); override for stress tests."""
    try:
        return max(1, int(_env("BLUEFOG_TIMELINE_QUEUE_CAPACITY",
                               "65536")))
    except ValueError:
        return 65536


def timeline_native() -> bool:
    """BLUEFOG_TIMELINE_NATIVE (default on): prefer the C++ lock-free
    ring writer when the native extension built; ``0`` forces the
    Python queue backend."""
    return _env("BLUEFOG_TIMELINE_NATIVE", "1") != "0"


def straggler_z_threshold() -> float:
    """BLUEFOG_STRAGGLER_Z (default 4.0): robust step-time z-score above
    which the fleet telemetry layer's
    :class:`~bluefog_tpu.observe.fleet.StragglerDetector` counts a rank
    as slow (flagged after ``patience`` consecutive observations)."""
    try:
        return float(_env("BLUEFOG_STRAGGLER_Z", "4.0"))
    except ValueError:
        return 4.0


def fuse_epilogues() -> bool:
    """BLUEFOG_FUSE_EPILOGUES (default on): whether
    :func:`bluefog_tpu.optim.functional.build_train_step` builds the
    FUSED per-bucket epilogue pipeline (quantize -> exchange ->
    dequantize -> guard-select -> health-norm composed into one pass
    per fusion-plan bucket).  ``0`` falls back to the pre-fusion
    builders where the guard's isfinite reduce, the health vector's
    norms, and the consensus distance each re-traverse the full param
    tree around the exchange — the escape hatch for debugging, and the
    golden reference path the epilogue parity matrix compares against
    (tests/test_epilogue.py)."""
    return _env("BLUEFOG_FUSE_EPILOGUES", "1") not in ("0", "false",
                                                       "False")


def hier_local_size():
    """BLUEFOG_HIER_LOCAL_SIZE (default unset): default intra-machine
    group width of the HIERARCHICAL neighbor exchange — when set (>= 1),
    :func:`bluefog_tpu.optim.functional.build_train_step` builds the
    two-level combine (exact ICI allreduce inside each machine of this
    many ranks, decentralized mixing of machine means across DCN) for
    cta/atc steps that did not pass ``hierarchical=`` /
    ``hierarchical_local_size=`` explicitly; the ``topology=`` /
    ``schedule=`` specs must then be MACHINE-level.  Unset/0 keeps the
    flat rank-level exchange.  Explicit builder arguments always win
    over this env default."""
    raw = _env("BLUEFOG_HIER_LOCAL_SIZE", "")
    try:
        v = int(raw)
    except ValueError:
        return None
    return v if v >= 1 else None


def mix_compress():
    """BLUEFOG_MIX_COMPRESS (default unset): default WIRE COMPRESSION
    mode of :func:`bluefog_tpu.optim.functional.build_train_step` for
    cta/atc steps that did not pass ``compress=`` explicitly —
    ``int8``, ``int8_sr``, ``bf16``, or ``topk`` (error-feedback
    compressed mixing; pair with :func:`mix_compress_ratio`).  Unset or
    unrecognized keeps the full-precision wire.  Explicit builder
    arguments always win over this env default."""
    raw = _env("BLUEFOG_MIX_COMPRESS", "").strip().lower()
    return raw if raw in ("int8", "int8_sr", "bf16", "topk") else None


def mix_compress_ratio():
    """BLUEFOG_MIX_COMPRESS_RATIO (default unset -> builder default):
    kept fraction of each bucket's elements for the error-feedback
    compressed mixing wire (``BLUEFOG_MIX_COMPRESS=topk`` or
    ``compress="topk"``), in (0, 1].  Values >= 1.0 mean "keep
    everything" and build the uncompressed exchange; out-of-range or
    unparsable values are ignored (``None``)."""
    raw = _env("BLUEFOG_MIX_COMPRESS_RATIO", "")
    try:
        v = float(raw)
    except ValueError:
        return None
    return v if v > 0 else None


def moe_capacity_factor() -> float:
    """BLUEFOG_MOE_CAPACITY_FACTOR (default 1.25): default expert
    capacity factor of :func:`bluefog_tpu.moe.layer.default_capacity`
    — each destination rank accepts ``ceil(factor * tokens / n)``
    tokens per source shard; batch-order overflow beyond it is dropped
    onto the residual path (the keep mask is traced data).  Explicit
    ``capacity=`` arguments always win over this env default."""
    try:
        v = float(_env("BLUEFOG_MOE_CAPACITY_FACTOR", "1.25"))
    except ValueError:
        return 1.25
    return v if v > 0 else 1.25


def kv_zero_on_free() -> bool:
    """BLUEFOG_KV_ZERO_ON_FREE (default OFF): whether
    :meth:`bluefog_tpu.serving.SlotPool.free` zeroes the freed slot's
    whole K/V cache.  The default resets only the slot's ``cache_index``
    leaves — correctness needs nothing more (everything above the index
    is invisible behind the causal mask and gets overwritten as the next
    request writes its own positions), and the full-slot zero is a
    whole-slot HBM write per retirement that also destroys K/V the
    prefix cache could have served.  ``1`` restores the old
    zero-everything behavior (a debugging aid: a zeroed pool makes
    "reuse leaves no trace" literal instead of masked)."""
    return _env("BLUEFOG_KV_ZERO_ON_FREE", "0") in ("1", "true", "True")


def prefix_cache_mb() -> int:
    """BLUEFOG_PREFIX_CACHE_MB (default 64): host-side byte budget of the
    serving prefix cache (:mod:`bluefog_tpu.serving.prefix_cache`), in
    MiB.  Evicted K/V chunks are retained up to this bound (LRU) so
    requests sharing a prompt prefix admit by copying cached chunks
    instead of re-running prefill.  0 disables retention."""
    try:
        return int(_env("BLUEFOG_PREFIX_CACHE_MB", "64"))
    except ValueError:
        return 64


def replica_stale_s() -> float:
    """BLUEFOG_REPLICA_STALE_S (seconds, default 0 = disabled): serving
    fleet staleness guard.  A replica that has not published a step
    heartbeat (``bf_serving_last_step_ts``) within this window is marked
    *suspect* by :class:`bluefog_tpu.serving.FleetRouter` — its gossip
    row is masked out and its score pinned to +inf, exactly like the
    explicit dead-mask path — until it steps again.  Replicas that have
    never stepped are exempt (cold replicas must stay routable)."""
    try:
        return float(_env("BLUEFOG_REPLICA_STALE_S", "0"))
    except ValueError:
        return 0.0


def router_retries() -> int:
    """BLUEFOG_ROUTER_RETRIES (default 0): extra full-fleet walks
    :meth:`FleetRouter.submit` makes after the first walk exhausts every
    live replica, separated by seeded exponential backoff
    (:func:`bluefog_tpu.serving.resilience.backoff_sleep`).  0 keeps the
    historical single-walk behavior: one pass, then ``FleetSaturated``."""
    try:
        return max(0, int(_env("BLUEFOG_ROUTER_RETRIES", "0")))
    except ValueError:
        return 0


def router_retry_base_s() -> float:
    """BLUEFOG_ROUTER_RETRY_BASE_S (seconds, default 0.05): base delay of
    the router's seeded exponential backoff between submit retry walks
    (attempt k sleeps ~ base * 2**k, jittered deterministically from the
    router seed and request id)."""
    try:
        return float(_env("BLUEFOG_ROUTER_RETRY_BASE_S", "0.05"))
    except ValueError:
        return 0.05


def router_cooldown_s() -> float:
    """BLUEFOG_ROUTER_COOLDOWN_S (seconds, default 0 = disabled): after a
    replica rejects repeated submits, the router demotes it to the back
    of the candidate walk for this long.  Cooldown only re-orders the
    walk — a cooling replica is still tried last, so cooldown can never
    manufacture a ``FleetSaturated`` on its own."""
    try:
        return float(_env("BLUEFOG_ROUTER_COOLDOWN_S", "0"))
    except ValueError:
        return 0.0


def elastic_bootstrap_rounds() -> int:
    """BLUEFOG_ELASTIC_BOOTSTRAP_ROUNDS (default 8): quarantined mixing
    rounds a joining rank's self-weight anneals over (0 -> its pristine
    weight) while bootstrapping by pulled neighbor averaging
    (:mod:`bluefog_tpu.elastic.bootstrap`).  More rounds = gentler
    re-entry; the first round is always a pure pull regardless."""
    try:
        return max(1, int(_env("BLUEFOG_ELASTIC_BOOTSTRAP_ROUNDS", "8")))
    except ValueError:
        return 8


def elastic_quarantine_threshold() -> float:
    """BLUEFOG_ELASTIC_QUARANTINE_THRESHOLD (default 1.0): max
    normalized bootstrap disagreement (joiner's L2 distance from the
    live mean, in units of the live ranks' own max deviation — see
    :func:`bluefog_tpu.elastic.bootstrap.disagreement`) for promotion
    to LIVE.  <= 1.0 means the joiner sits inside the live consensus
    cloud.  Until it clears, live receivers keep zero weight on the
    joiner — a half-synced value never leaks into the fleet."""
    try:
        return float(_env("BLUEFOG_ELASTIC_QUARANTINE_THRESHOLD", "1.0"))
    except ValueError:
        return 1.0


def topology_replan_window() -> int:
    """BLUEFOG_TOPOLOGY_REPLAN_WINDOW (steps, default 8): how often the
    topology control plane (:class:`bluefog_tpu.topology.control.
    TopologyControlPlane`) takes a telemetry window — per-edge
    byte/second DELTAS, straggler z snapshot, live-set — and re-scores
    the incumbent schedule against it.  Larger windows smooth noise;
    smaller ones react faster."""
    try:
        return max(1, int(_env("BLUEFOG_TOPOLOGY_REPLAN_WINDOW", "8")))
    except ValueError:
        return 8


def topology_replan_patience() -> int:
    """BLUEFOG_TOPOLOGY_REPLAN_PATIENCE (windows, default 2): consecutive
    DEGRADED telemetry windows before the control plane triggers a
    background re-synthesis — the debounce half of the hysteresis pair
    (one noisy window never re-plans).  A live-set transition (death,
    promotion) bypasses patience: membership is structural, not
    noise."""
    try:
        return max(1, int(_env("BLUEFOG_TOPOLOGY_REPLAN_PATIENCE", "2")))
    except ValueError:
        return 2


def topology_replan_degrade_ratio() -> float:
    """BLUEFOG_TOPOLOGY_REPLAN_DEGRADE (default 1.3): a telemetry window
    counts as degraded when some active edge's measured
    seconds-per-activation (normalized by its nominal link cost)
    exceeds the fleet-wide median by this factor — a RELATIVE test, so
    uniform load (every link equally busy) never trips it and the units
    of the seconds counters cancel out."""
    try:
        return float(_env("BLUEFOG_TOPOLOGY_REPLAN_DEGRADE", "1.3"))
    except ValueError:
        return 1.3


def topology_replan_margin() -> float:
    """BLUEFOG_TOPOLOGY_REPLAN_MARGIN (default 0.05): fractional
    cost-to-consensus improvement a synthesized candidate must show
    over the RE-SCORED incumbent to be accepted for a hot swap — the
    anti-flap half of the hysteresis pair (a candidate that merely
    ties the incumbent is noise, and swapping on noise would oscillate
    between near-equal plans)."""
    try:
        return float(_env("BLUEFOG_TOPOLOGY_REPLAN_MARGIN", "0.05"))
    except ValueError:
        return 0.05


def topology_replan_cooldown() -> int:
    """BLUEFOG_TOPOLOGY_REPLAN_COOLDOWN (steps, default 16): minimum
    steps between topology swaps (and after a rollback, before the
    next trigger may fire).  Bounds the worst-case swap rate no matter
    how noisy telemetry gets."""
    try:
        return max(0, int(_env("BLUEFOG_TOPOLOGY_REPLAN_COOLDOWN", "16")))
    except ValueError:
        return 16


def topology_replan_probation() -> int:
    """BLUEFOG_TOPOLOGY_REPLAN_PROBATION (steps, default 8): how long a
    freshly swapped-in schedule is on probation — the control plane
    watches the consensus-distance health signal and rolls back to the
    incumbent if it worsens past the pre-swap baseline; after this
    many clean steps the candidate is committed as the new
    incumbent."""
    try:
        return max(1, int(_env("BLUEFOG_TOPOLOGY_REPLAN_PROBATION", "8")))
    except ValueError:
        return 8


def fusion_threshold() -> int:
    """BLUEFOG_FUSION_THRESHOLD: max bytes of per-rank payload packed into
    one flat fusion buffer by the eager optimizers' communication
    (reference operations.cc:42-44 default 8 MB + tensor_queue.h:75-124).
    0 disables fusion (one collective per parameter leaf)."""
    return int(_env("BLUEFOG_FUSION_THRESHOLD", str(8 * 1024 * 1024)))


def skip_negotiate_default() -> bool:
    """BLUEFOG_SKIP_NEGOTIATE_STAGE — negotiation does not exist on TPU;
    the flag is kept so scripts that set it keep working
    (reference operations.cc:1149-1183)."""
    return _env("BLUEFOG_SKIP_NEGOTIATE_STAGE", "0") in ("1", "true", "True")


def stall_warning_time() -> float:
    """BLUEFOG_STALL_WARNING_TIME (seconds, default 60; <=0 disables) — how
    long a blocking wait may run before the stall watchdog logs a warning
    (reference STALL_WARNING_TIME operations.cc:47, watchdog :388-433)."""
    try:
        return float(_env("BLUEFOG_STALL_WARNING_TIME", "60"))
    except ValueError:
        return 60.0


def op_timeout() -> float:
    """BLUEFOG_OP_TIMEOUT (seconds, default 0; <=0 disables) — hard ceiling
    on any blocking wait (synchronize/barrier/win_wait/win_fence).  Where
    the stall watchdog only *warns* (BLUEFOG_STALL_WARNING_TIME), this
    RAISES ``BluefogError`` naming the stalled op and the stale processes
    from the heartbeat beacons, so a wedged collective fails fast instead
    of hanging the job forever."""
    try:
        return float(_env("BLUEFOG_OP_TIMEOUT", "0"))
    except ValueError:
        return 0.0


def ops_on_cpu() -> bool:
    """BLUEFOG_OPS_ON_CPU — run collectives on the host CPU backend instead
    of the accelerator (reference torch/mpi_ops.cc:48-50)."""
    return _env("BLUEFOG_OPS_ON_CPU", "0") in ("1", "true", "True")


# ------------------------------------------------------------------ #
# launcher / process-identity contract (the BLUEFOG_TPU_* vars bfrun
# exports into every child — bluefog_tpu/run/run.py _child_env)
# ------------------------------------------------------------------ #
def coordinator() -> str:
    """BLUEFOG_TPU_COORDINATOR: ``host:port`` of the jax.distributed
    coordinator; empty when not launched by bfrun (single process)."""
    return _env("BLUEFOG_TPU_COORDINATOR", "")


def num_processes() -> int:
    """BLUEFOG_TPU_NUM_PROCESSES (default 1): job size bfrun exported."""
    try:
        return int(_env("BLUEFOG_TPU_NUM_PROCESSES", "1"))
    except ValueError:
        return 1


def process_id():
    """BLUEFOG_TPU_PROCESS_ID as an int, or ``None`` when unset (or
    unparsable) — callers that REQUIRE an id under a coordinator
    (api._maybe_init_distributed) treat None as the error it is; the
    log formatter falls back to rank 0."""
    raw = _env("BLUEFOG_TPU_PROCESS_ID", "")
    try:
        return int(raw)
    except ValueError:
        return None


def engine_token() -> str:
    """BLUEFOG_TPU_ENGINE_TOKEN: shared secret the interactive-run
    engine processes require on every control connection
    (bluefog_tpu/run/engines.py); empty disables nothing — an empty
    token still HMACs, it is just guessable."""
    return _env("BLUEFOG_TPU_ENGINE_TOKEN", "")


def state_dir() -> str:
    """BLUEFOG_TPU_STATE_DIR (default ``~/.bluefog_tpu``), expanded:
    where ``ibfrun`` keeps its per-profile engine state files."""
    return os.path.expanduser(_env("BLUEFOG_TPU_STATE_DIR",
                                   "~/.bluefog_tpu"))


def chip_peak_tflops_override():
    """BLUEFOG_CHIP_PEAK_TFLOPS: per-chip peak bf16 TFLOP/s override for
    :func:`bluefog_tpu.benchutil.chip_peak_flops` (auditing a TPU target
    from a CPU host).  ``None``/0 when unset or empty."""
    raw = _env("BLUEFOG_CHIP_PEAK_TFLOPS", "")
    return float(raw) if raw else None


def chip_hbm_gbps_override():
    """BLUEFOG_CHIP_HBM_GBPS: per-chip HBM GB/s override for
    :func:`bluefog_tpu.benchutil.chip_hbm_bandwidth`; same convention as
    :func:`chip_peak_tflops_override`."""
    raw = _env("BLUEFOG_CHIP_HBM_GBPS", "")
    return float(raw) if raw else None


def environ_passthrough(base=None) -> dict:
    """Snapshot of the process environment (or ``base`` when given) for
    the launchers' pass-through forwarding — bfrun/ibfrun filter this
    by ``PASS_PREFIXES`` when building child/remote environments.  The
    one sanctioned whole-environment read outside this module's named
    accessors, kept here so the env-access surface stays auditable."""
    return dict(os.environ if base is None else base)


def configure_host_platform(devices: int = 8) -> None:
    """Force the JAX CPU backend with ``devices`` virtual devices —
    the same environment tests/conftest.py pins — by setting
    ``JAX_PLATFORMS=cpu`` and merging
    ``--xla_force_host_platform_device_count`` into ``XLA_FLAGS``.
    Must run BEFORE the first jax import; used by ``bfcheck`` so the
    static sweep can build 8-rank programs anywhere.  Values already
    present in the environment win."""
    env = os.environ
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={devices}"
        ).strip()
