"""One-sided ("window") gossip ops — the TPU mailbox subsystem.

The reference implements windows with MPI RMA (MPI_Put/Get/Accumulate under
passive-target locks, reference bluefog/common/mpi_controller.cc:795-1392)
or, on NCCL, an emulation with per-peer communicators and a passive recv
thread (reference nccl_controller.cc:1261-1660).  The *Python-visible* state,
however, is simply per-in-neighbor receive buffers
(``WinTorchStorageManager``, reference torch/mpi_win_ops.cc:83-105) — and
that is exactly what this module keeps, as device-resident mailboxes:

* ``value``     [n, *shape]      rank-major window tensors
* ``mailbox``   [n, d, *shape]   slot [dst, k] = what dst's k-th (sorted)
  in-neighbor last sent (d = max in-degree: in-degree-bounded, like the
  reference's per-in-neighbor tensors — never a dense [n, n] buffer)
* ``versions``  [n, d] int32     bumped on put/get/accumulate, cleared on update
* ``p``         [n] f64          associated push-sum scalar (init 1.0)
* ``p_mailbox`` [n, d] f64       mailbox for p

``win_put`` lowers to one ``lax.ppermute`` per shift class of the destination
set, writing into the receiver's slot for the sender; ``win_update`` is a
local weighted combine.  Asynchrony model: the reference's wall-clock
asynchrony (ranks progress independently) becomes JAX async dispatch —
puts/updates from step k+1 may be in flight while step k's results are
unread, but within one jitted program order is total.  The distributed mutex
(reference mpi_controller.cc:1594-1663) is therefore unnecessary; the
``win_mutex``/``win_lock`` context managers are kept as no-ops for API
parity.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import networkx as nx
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bluefog_tpu.context import AXIS, BluefogContext, BluefogError, host_fetch
from bluefog_tpu.parallel.collectives import (
    class_recv_weights as _class_recv_weights,
    edge_structure as _edge_structure,
)
from bluefog_tpu.topology.spec import DynamicTopology

P_DTYPE = jnp.float64  # associated-P kept in f64 on CPU, f32 on TPU (below)


def _p_dtype():
    return jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32


class Window:
    """Device-resident state for one named window.

    Mailboxes are IN-DEGREE-BOUNDED: per rank the receive buffer has
    ``max_in_degree`` slots ordered by sorted in-neighbor rank (exactly
    the reference's WinTorchStorageManager, which allocates one local
    tensor per in-neighbor, mpi_win_ops.cc:83-105) — per-shard memory is
    O(d * |x|), never the dense O(n * |x|) that would OOM a pod."""

    def __init__(
        self,
        ctx: BluefogContext,
        name: str,
        value: jax.Array,
        zero_init: bool,
    ):
        n = ctx.size()
        self.name = name
        self.ctx = ctx
        self.shape = value.shape[1:]
        self.dtype = value.dtype
        self.value = value
        # The topology is pinned while windows are alive (reference
        # basics.py refuses set_topology with registered windows).
        self.in_neighbors = {
            r: ctx.in_neighbor_ranks(r) for r in range(n)
        }
        self.out_neighbors = {
            r: ctx.out_neighbor_ranks(r) for r in range(n)
        }
        self.in_lists = [sorted(self.in_neighbors[r]) for r in range(n)]
        self.d_max = max((len(l) for l in self.in_lists), default=0) or 1

        sharding = NamedSharding(ctx.mesh, P(AXIS))
        # Mailbox init: each slot holds its in-neighbor's value (a fresh
        # put's no-op state), or zeros
        # (reference torch/mpi_win_ops.cc:88-100 RegisterWinName).
        if zero_init:
            mailbox = jnp.zeros((n, self.d_max) + self.shape,
                                dtype=value.dtype)
            self.mailbox = jax.device_put(mailbox, sharding)
        else:
            from bluefog_tpu.parallel import collectives as C

            spec = ctx.topology_spec()
            d_spec = max((len(l) for l in C.in_neighbor_lists(spec)),
                         default=0)

            def fill(x):
                out = C.neighbor_allgather_padded(x[0], spec, AXIS)[None]
                pad = self.d_max - d_spec
                if pad > 0:  # degenerate edgeless topology: d_max floor 1
                    out = jnp.concatenate(
                        [out, jnp.zeros(out.shape[:1] + (pad,)
                                        + out.shape[2:], out.dtype)], 1)
                return out

            sm = jax.shard_map(fill, mesh=ctx.mesh, in_specs=P(AXIS),
                               out_specs=P(AXIS), check_vma=False)
            self.mailbox = jax.jit(sm)(value)
        self.versions = jax.device_put(
            jnp.zeros((n, self.d_max), dtype=jnp.int32), sharding
        )
        self.p = jax.device_put(jnp.ones((n,), dtype=_p_dtype()), sharding)
        self.p_mailbox = jax.device_put(
            jnp.zeros((n, self.d_max), dtype=_p_dtype()), sharding
        )


class WindowManager:
    """All windows of a context + the jitted mailbox programs."""

    def __init__(self, ctx: BluefogContext):
        self.ctx = ctx
        self._lock = threading.Lock()
        self._win_handle_map: Dict[int, Tuple[str, object]] = {}
        self._next_handle = 0

    # -------------------------------------------------------------- #
    # lifecycle
    # -------------------------------------------------------------- #
    def create(self, tensor, name: str, zero_init: bool = False) -> bool:
        ctx = self.ctx
        if name in ctx.windows:
            return False
        value = ctx.rank_sharded(tensor)
        ctx.windows[name] = Window(ctx, name, value, zero_init)
        return True

    def free(self, name: Optional[str] = None) -> bool:
        if name is None:
            self.ctx.windows.clear()
            return True
        if name not in self.ctx.windows:
            return False
        del self.ctx.windows[name]
        return True

    def names(self) -> List[str]:
        return sorted(self.ctx.windows)

    def window(self, name: str) -> Window:
        if name not in self.ctx.windows:
            raise BluefogError(f"Window '{name}' does not exist.")
        return self.ctx.windows[name]

    # -------------------------------------------------------------- #
    # handles (reference win_handle_manager, torch/mpi_win_ops.cc)
    # -------------------------------------------------------------- #
    def _register(self, name: str, arrays) -> int:
        with self._lock:
            handle = self._next_handle
            self._next_handle += 1
            self._win_handle_map[handle] = (name, arrays)
            return handle

    def wait(self, handle: int) -> bool:
        from bluefog_tpu.context import timed_wait

        with self._lock:
            entry = self._win_handle_map.pop(handle, None)
        if entry is None:
            return False
        timed_wait(f"win.{entry[0]}",
                   lambda: jax.block_until_ready(entry[1]))
        return True

    def poll(self, handle: int) -> bool:
        with self._lock:
            entry = self._win_handle_map.get(handle)
        if entry is None:
            raise BluefogError(f"Unknown window handle {handle}")
        leaves = jax.tree_util.tree_leaves(entry[1])
        return all(leaf.is_ready() for leaf in leaves)

    # -------------------------------------------------------------- #
    # weight resolution
    # -------------------------------------------------------------- #
    def _resolve_dst(self, win: Window, dst_weights) -> DynamicTopology:
        """Edges (src -> dst) with sender-side weights for put/accumulate.
        Default: all out-neighbors with weight 1.0
        (reference torch/mpi_ops.py:1190-1196)."""
        n = self.ctx.size()
        from bluefog_tpu.context import WeightArg

        per_rank = WeightArg.per_rank(dst_weights, n, "dst")
        edge_weights: Dict[Tuple[int, int], float] = {}
        for src in range(n):
            entry = per_rank[src]
            if entry is None:
                entry = {d: 1.0 for d in win.out_neighbors[src]}
            elif not isinstance(entry, dict):
                entry = {int(d): 1.0 for d in entry}
            for dst, w in entry.items():
                dst = int(dst)
                if dst not in win.out_neighbors[src]:
                    raise ValueError(
                        "The key of dst_weights should only contain ranks "
                        "that belong to out-neighbors (self-rank is not "
                        "allowed)."
                    )
                edge_weights[(src, dst)] = float(w)
        return DynamicTopology.from_edges(n, edge_weights)

    def _resolve_src(self, win: Window, src_weights) -> DynamicTopology:
        """Edges (src -> dst) with receiver-side weights for get.
        Default: all in-neighbors with weight 1.0
        (reference torch/mpi_ops.py:1249-1258)."""
        n = self.ctx.size()
        from bluefog_tpu.context import WeightArg

        per_rank = WeightArg.per_rank(src_weights, n, "src")
        edge_weights: Dict[Tuple[int, int], float] = {}
        for dst in range(n):
            entry = per_rank[dst]
            if entry is None:
                entry = {s: 1.0 for s in win.in_neighbors[dst]}
            elif not isinstance(entry, dict):
                entry = {int(s): 1.0 for s in entry}
            for src, w in entry.items():
                src = int(src)
                if src not in win.in_neighbors[dst]:
                    raise ValueError(
                        "The key of src_weights should only contain ranks "
                        "that belong to in-neighbors."
                    )
                edge_weights[(src, dst)] = float(w)
        return DynamicTopology.from_edges(n, edge_weights)

    # -------------------------------------------------------------- #
    # ops
    # -------------------------------------------------------------- #
    def put(
        self,
        tensor,
        name: str,
        self_weight: Optional[float] = None,
        dst_weights=None,
        require_mutex: bool = False,
        accumulate: bool = False,
    ) -> int:
        """win_put / win_accumulate.  Sends ``tensor[src] * w(src->dst)``
        into dst's slot for src (replace for put, add for accumulate), bumps
        the version, then scales the local window tensor by ``self_weight``
        (reference torch/mpi_ops.py:1161-1199; wire
        mpi_controller.cc:952-1035).  Returns a handle."""
        ctx = self.ctx
        win = self.window(name)
        x = ctx.rank_sharded(tensor)
        if self_weight is None:
            self_weight = 1.0
        from bluefog_tpu.context import WeightArg

        sw = jnp.asarray(
            np.asarray(WeightArg.per_rank(self_weight, ctx.size(), "self"),
                       dtype=np.float32))
        spec = self._resolve_dst(win, dst_weights)
        associated_p = ctx.win_ops_with_associated_p

        # The compiled program is keyed on the edge STRUCTURE only; the
        # per-edge and self weights enter as traced operands, so a dynamic
        # gossip schedule that varies weights every step reuses ONE
        # compilation (round-1 hazard: weights in the cache key retraced
        # per step with unbounded cache growth).
        structure = _edge_structure(spec)
        wvecs = _class_recv_weights(spec)
        key = ("win_put", name, spec.edges, bool(accumulate), associated_p,
               x.shape, str(x.dtype))
        fn = ctx._op_cache.get(key)
        if fn is None:
            tables = _slot_tables(structure, win.in_lists)
            fn = jax.jit(
                jax.shard_map(
                    lambda xx, mb, vv, pp, pmb, wv, sv: _put_kernel(
                        xx, mb, vv, pp, pmb, wv, sv, structure, tables,
                        accumulate, associated_p
                    ),
                    mesh=ctx.mesh,
                    in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                              P(), P()),
                    out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                    check_vma=False,
                )
            )
            ctx._op_cache[key] = fn
        new_value, win.mailbox, win.versions, win.p, win.p_mailbox = fn(
            x, win.mailbox, win.versions, win.p, win.p_mailbox, wvecs, sw
        )
        win.value = new_value
        return self._register(name, (new_value, win.mailbox))

    def get(
        self,
        name: str,
        src_weights=None,
        require_mutex: bool = False,
    ) -> int:
        """win_get: fetch src's *window tensor* scaled by the receiver-side
        weight into my slot for src (reference torch/mpi_ops.py:1229-1261;
        wire mpi_controller.cc:1122-1183)."""
        ctx = self.ctx
        win = self.window(name)
        spec = self._resolve_src(win, src_weights)
        associated_p = ctx.win_ops_with_associated_p

        structure = _edge_structure(spec)
        wvecs = _class_recv_weights(spec)
        key = ("win_get", name, spec.edges, associated_p,
               win.value.shape, str(win.value.dtype))
        fn = ctx._op_cache.get(key)
        if fn is None:
            tables = _slot_tables(structure, win.in_lists)
            fn = jax.jit(
                jax.shard_map(
                    lambda xx, mb, vv, pp, pmb, wv: _get_kernel(
                        xx, mb, vv, pp, pmb, wv, structure, tables,
                        associated_p
                    ),
                    mesh=ctx.mesh,
                    in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                              P()),
                    out_specs=(P(AXIS), P(AXIS), P(AXIS)),
                    check_vma=False,
                )
            )
            ctx._op_cache[key] = fn
        win.mailbox, win.versions, win.p_mailbox = fn(
            win.value, win.mailbox, win.versions, win.p, win.p_mailbox, wvecs
        )
        return self._register(name, (win.mailbox,))

    def update(
        self,
        name: str,
        self_weight: Optional[float] = None,
        neighbor_weights=None,
        reset: bool = False,
        clone: bool = False,
        require_mutex: bool = False,
    ) -> jax.Array:
        """win_update: in-place weighted combine of the window tensor with
        the mailbox slots (reference torch/mpi_ops.py:1081-1153 +
        torch/mpi_win_ops.cc:345-426).  Returns the new rank-major tensor
        (also stored as the window value unless ``clone``)."""
        ctx = self.ctx
        win = self.window(name)
        n = ctx.size()

        if (self_weight is None) != (neighbor_weights is None):
            raise ValueError(
                "Arguments self_weight and neighbor_weights have to be "
                "presented at the same time"
            )
        # Resolve per-rank combine weights (reference mpi_ops.py:1123-1148).
        from bluefog_tpu.context import WeightArg

        if self_weight is None:
            self_w = []
            edge_weights = {}
            weight_matrix = (
                nx.to_numpy_array(ctx.load_topology())
                if ctx.is_topo_weighted() else None
            )
            for dst in range(n):
                if weight_matrix is not None:
                    s = float(weight_matrix[dst, dst])
                    nbrs = {
                        int(src): float(weight_matrix[src, dst])
                        for src in win.in_neighbors[dst]
                    }
                else:
                    nbr_list = win.in_neighbors[dst]
                    s = 1.0 / (len(nbr_list) + 1)
                    nbrs = {r: s for r in nbr_list}
                self_w.append(s)
                for src, w in nbrs.items():
                    edge_weights[(src, dst)] = float(w)
        else:
            selfs = WeightArg.per_rank(self_weight, n, "self")
            nbrs_per = WeightArg.per_rank(neighbor_weights, n, "src")
            self_w = [s if s is not None else 0.0 for s in selfs]
            edge_weights = {}
            for dst in range(n):
                entry = nbrs_per[dst] or {}
                if not isinstance(entry, dict):
                    raise ValueError(
                        "Argument neighbor_weights has to be a dictionary "
                        "map from the (in-)neighbor rank to the weights."
                    )
                for src, w in entry.items():
                    src = int(src)
                    if src not in win.in_neighbors[dst]:
                        raise ValueError(
                            "The key of weights should only contain the "
                            "ranks that belong to in-neighbors and self rank."
                        )
                    edge_weights[(src, dst)] = float(w)
        spec = DynamicTopology.from_edges(n, edge_weights, self_w)
        associated_p = ctx.win_ops_with_associated_p

        structure = _edge_structure(spec)
        wvecs = _class_recv_weights(spec)
        sw = jnp.asarray(np.asarray(spec.self_weight_values, np.float32))
        key = ("win_update", name, spec.edges, bool(reset), associated_p,
               win.value.shape, str(win.value.dtype))
        fn = ctx._op_cache.get(key)
        if fn is None:
            tables = _slot_tables(structure, win.in_lists)
            fn = jax.jit(
                jax.shard_map(
                    lambda xx, mb, vv, pp, pmb, wm, sv: _update_kernel(
                        xx, mb, vv, pp, pmb, wm, sv, structure, tables,
                        reset, associated_p
                    ),
                    mesh=ctx.mesh,
                    in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS),
                              P(), P()),
                    out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
                    check_vma=False,
                )
            )
            ctx._op_cache[key] = fn
        new_value, mailbox, versions, p, p_mailbox = fn(
            win.value, win.mailbox, win.versions, win.p, win.p_mailbox,
            wvecs, sw
        )
        win.mailbox, win.versions, win.p_mailbox = mailbox, versions, p_mailbox
        win.p = p
        if not clone:
            win.value = new_value
        return new_value

    def set_value(self, name: str, tensor):
        """Rebind the window tensor (the reference mutates the registered
        torch tensor in place; functional JAX callers set it explicitly)."""
        win = self.window(name)
        win.value = self.ctx.rank_sharded(tensor)

    def versions_of(self, name: str, rank: Optional[int] = None) -> Dict[int, int]:
        win = self.window(name)
        r = self.ctx.rank() if rank is None else rank
        vers = host_fetch(win.versions)
        return {s: int(vers[r, win.in_lists[r].index(s)])
                for s in win.in_neighbors[r]}

    def associated_p(self, name: str, rank: Optional[int] = None) -> float:
        win = self.window(name)
        r = self.ctx.rank() if rank is None else rank
        return float(host_fetch(win.p)[r])


# ------------------------------------------------------------------ #
# shard-level kernels (shapes: x [1,*s]; mailbox [1,n,*s]; ver [1,n];
# p [1]; p_mailbox [1,n])
#
# Weights are TRACED operands (a [n_classes, n] per-shift-class recv
# vector stack + [n] self vector — O(n * classes), never a dense [n, n]
# matrix); only the edge structure (which edges exist) is baked into the
# compiled program — so schedules that vary weights per step reuse one
# compilation.
# ------------------------------------------------------------------ #


def _slot_tables(structure: DynamicTopology, in_lists) -> list:
    """Per shift class, a length-n table: the mailbox SLOT rank d uses for
    this class's incoming edge (position of the source in d's sorted
    in-neighbor list), or -1 when d has no edge in the class.  Host-side,
    trace-time; ``in_lists`` is the WINDOW topology's in-neighbor lists
    (op edge sets are validated subsets of it)."""
    n = structure.size
    tables = []
    for cls in structure.shift_classes:
        tbl = []
        for dst in range(n):
            if cls.recv_weights[dst] != 0.0:
                tbl.append(in_lists[dst].index((dst - cls.shift) % n))
            else:
                tbl.append(-1)
        tables.append(tuple(tbl))
    return tables


def _put_kernel(x, mailbox, versions, p, p_mailbox, wvecs, self_weights,
                structure, tables, accumulate, associated_p):
    n = structure.size
    idx = lax.axis_index(AXIS)
    xs = x[0]
    mb = mailbox[0]
    ver = versions[0]
    pv = p[0]
    pmb = p_mailbox[0]
    for c, cls in enumerate(structure.shift_classes):
        # sender-side scale: the receiver's weight for this class, read
        # at my destination (me + shift)
        w_send = wvecs[c, (idx + cls.shift) % n].astype(jnp.float32)
        sent = lax.ppermute(
            (xs.astype(jnp.float32) * w_send).astype(xs.dtype),
            AXIS, cls.perm)
        slot_c = jnp.asarray(tables[c], jnp.int32)[idx]
        has = slot_c >= 0
        slot = jnp.maximum(slot_c, 0)
        cur = lax.dynamic_index_in_dim(mb, slot, 0, keepdims=False)
        new_slot = jnp.where(has, cur + sent if accumulate else sent, cur)
        mb = lax.dynamic_update_index_in_dim(mb, new_slot, slot, 0)
        ver = lax.dynamic_update_index_in_dim(
            ver, jnp.where(has, ver[slot] + 1, ver[slot]), slot, 0
        )
        if associated_p:
            p_sent = lax.ppermute(pv * w_send.astype(pv.dtype),
                                  AXIS, cls.perm)
            p_slot = pmb[slot]
            new_p = jnp.where(has, p_slot + p_sent if accumulate else p_sent, p_slot)
            pmb = lax.dynamic_update_index_in_dim(pmb, new_p, slot, 0)
    sw = self_weights.astype(jnp.float32)[idx]
    new_x = (xs.astype(jnp.float32) * sw).astype(xs.dtype)
    new_p_val = pv * sw.astype(pv.dtype) if associated_p else pv
    return (new_x[None], mb[None], ver[None], new_p_val[None], pmb[None])


def _get_kernel(x, mailbox, versions, p, p_mailbox, wvecs, structure,
                tables, associated_p):
    idx = lax.axis_index(AXIS)
    xs = x[0]
    mb = mailbox[0]
    ver = versions[0]
    pv = p[0]
    pmb = p_mailbox[0]
    for c, cls in enumerate(structure.shift_classes):
        fetched = lax.ppermute(xs, AXIS, cls.perm)
        # receiver-side scale: my weight for this class
        recv_w = wvecs[c, idx].astype(jnp.float32)
        slot_c = jnp.asarray(tables[c], jnp.int32)[idx]
        has = slot_c >= 0
        slot = jnp.maximum(slot_c, 0)
        cur = lax.dynamic_index_in_dim(mb, slot, 0, keepdims=False)
        scaled = (fetched.astype(jnp.float32) * recv_w).astype(xs.dtype)
        mb = lax.dynamic_update_index_in_dim(
            mb, jnp.where(has, scaled, cur), slot, 0
        )
        ver = lax.dynamic_update_index_in_dim(
            ver, jnp.where(has, ver[slot] + 1, ver[slot]), slot, 0
        )
        if associated_p:
            p_fetched = lax.ppermute(pv, AXIS, cls.perm)
            pmb = lax.dynamic_update_index_in_dim(
                pmb,
                jnp.where(has, p_fetched * recv_w.astype(pv.dtype),
                          pmb[slot]),
                slot, 0,
            )
    return (mb[None], ver[None], pmb[None])


def _update_kernel(x, mailbox, versions, p, p_mailbox, wvecs, self_weights,
                   structure, tables, reset, associated_p):
    idx = lax.axis_index(AXIS)
    xs = x[0]
    mb = mailbox[0]
    ver = versions[0]
    pv = p[0]
    pmb = p_mailbox[0]
    d_max = mb.shape[0]

    self_w = self_weights.astype(jnp.float32)[idx]
    acc = xs.astype(jnp.float32) * self_w
    new_p = pv * self_w.astype(pv.dtype) if associated_p else pv
    # structural inclusion mask per slot (which slots this update
    # consumes) — a declared 0.0-weight edge still counts as read
    included = jnp.zeros((d_max,), bool)
    for c, cls in enumerate(structure.shift_classes):
        slot_c = jnp.asarray(tables[c], jnp.int32)[idx]
        has = slot_c >= 0
        slot = jnp.maximum(slot_c, 0)
        w = jnp.where(has, wvecs[c, idx], 0.0)
        cur = lax.dynamic_index_in_dim(mb, slot, 0, keepdims=False)
        acc = acc + cur.astype(jnp.float32) * w
        if associated_p:
            new_p = new_p + pmb[slot] * w.astype(pv.dtype)
        included = included.at[slot].set(included[slot] | has)
    new_x = acc.astype(xs.dtype)

    if reset:
        shape_ones = (d_max,) + (1,) * (mb.ndim - 1)
        keep = (~included).astype(mb.dtype).reshape(shape_ones)
        mb = mb * keep
        ver = jnp.where(included, 0, ver)
        if associated_p:
            pmb = jnp.where(included, 0.0, pmb)
    else:
        # Reading via update clears versions for the slots it consumed
        # (reference mpi_controller.cc:1284-1392 version windows).
        ver = jnp.where(included, 0, ver)

    return (new_x[None], mb[None], ver[None], new_p[None], pmb[None])


@contextmanager
def win_mutex_ctx(manager: WindowManager, name: str, for_self=False,
                  ranks=None):
    """Distributed-mutex parity shim: SPMD program order already serializes
    window reads/writes within a step (reference mutex:
    mpi_controller.cc:1594-1663)."""
    manager.window(name)  # validate
    yield


@contextmanager
def win_lock_ctx(manager: WindowManager, name: str):
    """RMA-epoch parity shim (reference mpi_ops.py win_lock)."""
    manager.window(name)  # validate
    yield
