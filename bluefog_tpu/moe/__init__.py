"""Mixture-of-experts expert parallelism over the compiled all-to-all.

The topology compiler synthesizes the dispatch schedule
(:func:`bluefog_tpu.topology.compiler.compile_all_to_all`); this
package is the workload side: :mod:`bluefog_tpu.moe.dispatch` lowers a
schedule to the exact ``lax.ppermute`` program the compiler predicted
(byte-for-byte — the HLO tests hold it there) and owns the traced
``(route_table, capacity_mask)`` resilience data, and
:mod:`bluefog_tpu.moe.layer` is a small top-1-routed expert layer with
capacity-factor overflow as traced data.  Expert weights stay
rank-local; everything else mixes through the ordinary
``build_train_step(..., moe=MoEConfig(...))`` epilogue.
"""

from bluefog_tpu.moe.dispatch import (
    DispatchPlan,
    all_to_all_dispatch,
    capacity_mask_of,
    default_route_table,
    dispatch_plan,
    expert_owner,
    heal_route_table,
    naive_all_to_all,
)
from bluefog_tpu.moe.layer import (
    default_capacity,
    init_moe_params,
    make_moe_loss,
    moe_apply,
)

__all__ = [
    "DispatchPlan",
    "all_to_all_dispatch",
    "capacity_mask_of",
    "default_route_table",
    "dispatch_plan",
    "expert_owner",
    "heal_route_table",
    "naive_all_to_all",
    "default_capacity",
    "init_moe_params",
    "make_moe_loss",
    "moe_apply",
]
