"""A small expert-sharded MoE layer: top-1 router, capacity-factor
overflow as traced data, dispatch over the compiled all-to-all.

Each rank hosts ONE expert replica (``dispatch.expert_owner``); the
router and any surrounding dense weights are SHARED consensus state
(mixed by the ordinary neighbor epilogue), while the ``expert`` subtree
stays rank-local — ``build_train_step(..., moe=MoEConfig(...))`` makes
that split.  Routing decisions, capacity overflow and expert liveness
are all TRACED DATA (``route_table``, ``capacity_mask``, the keep
mask), so membership churn and re-plans never recompile.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from bluefog_tpu import config as _config
from bluefog_tpu.moe.dispatch import DispatchPlan, all_to_all_dispatch

_WEIGHT_AUTHORITY = True

__all__ = [
    "default_capacity",
    "init_moe_params",
    "moe_apply",
    "make_moe_loss",
]


def default_capacity(tokens_per_rank: int, n_ranks: int,
                     factor: Optional[float] = None) -> int:
    """Per-destination shard depth: ``ceil(factor * tokens / n)``,
    ``factor`` defaulting to the ``BLUEFOG_MOE_CAPACITY_FACTOR`` knob.
    Every destination rank receives at most this many tokens from each
    source — the static shard shape the wire carries."""
    if factor is None:
        factor = _config.moe_capacity_factor()
    if factor <= 0:
        raise ValueError(f"capacity factor must be > 0, got {factor}")
    return max(1, math.ceil(factor * tokens_per_rank / n_ranks))


def init_moe_params(key: jax.Array, d_model: int, d_hidden: int,
                    n_experts: int):
    """One rank's parameter tree: a shared router head plus the LOCAL
    expert MLP.  Build the rank-major stack by vmapping over per-rank
    keys; the ``expert`` subtree is what ``MoEConfig`` excludes from
    mixing."""
    k_r, k_i, k_o = jax.random.split(key, 3)
    s_in = 1.0 / math.sqrt(d_model)
    s_hid = 1.0 / math.sqrt(d_hidden)
    return {
        "router": {
            "w": (jax.random.normal(k_r, (d_model, n_experts),
                                    jnp.float32) * s_in),
        },
        "expert": {
            "wi": (jax.random.normal(k_i, (d_model, d_hidden),
                                     jnp.float32) * s_in),
            "wo": (jax.random.normal(k_o, (d_hidden, d_model),
                                     jnp.float32) * s_hid),
        },
    }


def moe_apply(params, tokens: jax.Array, route_row: jax.Array,
              capacity_mask: jax.Array, *, plan: DispatchPlan,
              axis_name: str, capacity: int,
              wire_dtype: Optional[str] = None,
              ) -> Tuple[jax.Array, jax.Array]:
    """One MoE layer on this rank's ``tokens [B, D]``: route top-1,
    pack per-destination shards up to ``capacity`` (batch-order
    overflow drop — the keep mask is returned as traced data), run the
    compiled dispatch, apply the LOCAL expert MLP to everything that
    arrived, and retrace the wire back (``plan.transpose()``) for the
    gate-weighted combine.  Dropped and dead-routed tokens pass
    through on the residual path.

    ``route_row [n_experts]`` is THIS rank's row of the route table
    (rank-major like every other per-rank operand — heals swap the
    stacked ``[n, n_experts]`` table wholesale) and ``capacity_mask``
    the full ``[n]`` liveness vector.  Returns
    ``(output [B, D], keep [B] bool)``.
    """
    n = plan.n

    logits = tokens @ params["router"]["w"]            # [B, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(logits, axis=-1)               # [B]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]
    dst = route_row[expert]                            # [B] traced

    # capacity: position of each token within its destination group,
    # in batch order (deterministic — the overflow drop set is a pure
    # function of the batch and the route data)
    dst_onehot = jax.nn.one_hot(dst, n, dtype=tokens.dtype)  # [B, n]
    before = jnp.cumsum(dst_onehot, axis=0) - dst_onehot
    pos = jnp.sum(before * dst_onehot, axis=1).astype(jnp.int32)
    alive = capacity_mask[dst] > 0
    keep = (pos < capacity) & alive                    # [B]

    comb = (dst_onehot[:, :, None]
            * jax.nn.one_hot(pos, capacity, dtype=tokens.dtype)[:, None, :]
            * keep[:, None, None].astype(tokens.dtype))  # [B, n, C]
    shards = jnp.einsum("bnc,bd->ncd", comb, tokens)   # [n, C, D]

    arrived = all_to_all_dispatch(shards, plan, axis_name,
                                  wire_dtype=wire_dtype)
    flat = arrived.reshape(n * capacity, -1)
    hidden = jax.nn.relu(flat @ params["expert"]["wi"])
    processed = (hidden @ params["expert"]["wo"]).reshape(arrived.shape)
    returned = all_to_all_dispatch(processed, plan.transpose(),
                                   axis_name, wire_dtype=wire_dtype)

    combined = jnp.einsum("bnc,ncd->bd", comb, returned)
    out = tokens + combined * gate[:, None]
    return out, keep


def make_moe_loss(plan: DispatchPlan, axis_name: str, capacity: int,
                  wire_dtype: Optional[str] = None):
    """``loss_fn(params, batch)`` for ``build_train_step``: ``batch``
    is ``(tokens, route_row, capacity_mask)``, every leaf RANK-MAJOR
    (tokens ``[n, B, D]``, the route table ``[n, n_experts]``, the
    liveness mask tiled ``[n, n]``) so the default batch specs shard
    all three — the route data is ordinary traced batch data, and
    heals swap it without recompiling."""

    def loss_fn(params, batch):
        tokens, route_row, capacity_mask = batch
        out, _ = moe_apply(params, tokens, route_row, capacity_mask,
                           plan=plan, axis_name=axis_name,
                           capacity=capacity, wire_dtype=wire_dtype)
        return jnp.mean(jnp.square(out - tokens))

    return loss_fn
