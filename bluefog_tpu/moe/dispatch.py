"""Expert dispatch: the compiled all-to-all on the wire.

``compile_all_to_all`` emits rounds as ``DynamicTopology`` specs; this
module lowers them to the EXACT collective program the compiler's
``predicted_collectives`` states — the same fusion rule, applied to the
same pair lists, so the HLO contract tests can hold the lowering to the
prediction permute-for-permute and byte-for-byte:

  * a round whose union pair list has all-unique srcs AND dsts fuses
    into ONE ``lax.ppermute`` carrying the full per-destination shard;
  * otherwise the round issues one ``lax.ppermute`` per rank-space
    shift class (each pair's payload depends only on the pair — src
    sends the shard addressed to dst — so class grouping is free to
    mix pairs from different torus shifts).

Resilience is DATA, not structure: the wire schedule is static for the
pod shape, and an expert machine's death only rewrites the traced
``(route_table, capacity_mask)`` operands (:func:`heal_route_table`),
so a kill→heal cycle never recompiles — the same shape-stability
contract the mixing weights already obey.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# Static route/capacity tables built here are communication-authority
# data the jaxpr checker must treat like comm weights, not model state.
_WEIGHT_AUTHORITY = True

__all__ = [
    "DispatchPlan",
    "dispatch_plan",
    "all_to_all_dispatch",
    "naive_all_to_all",
    "expert_owner",
    "default_route_table",
    "heal_route_table",
    "capacity_mask_of",
]

Pair = Tuple[int, int]


@dataclasses.dataclass(frozen=True)
class DispatchPlan:
    """The host-side lowering plan of one all-to-all schedule: per
    round, the ppermute groups (pair tuples) the dispatch issues, in
    emission order.  ``transpose()`` is the RETURN path — the same
    groups with every pair reversed, rounds in reverse order — so the
    combine retraces the dispatch wire exactly."""

    n: int
    rounds: Tuple[Tuple[Tuple[Pair, ...], ...], ...]

    @property
    def permutes_per_period(self) -> int:
        return sum(len(groups) for groups in self.rounds)

    def transpose(self) -> "DispatchPlan":
        return DispatchPlan(
            n=self.n,
            rounds=tuple(
                tuple(tuple((d, s) for (s, d) in group)
                      for group in groups)
                for groups in reversed(self.rounds)))


def dispatch_plan(schedule: Sequence) -> DispatchPlan:
    """Lower a compiled a2a schedule (``DynamicTopology`` rounds, e.g.
    ``CompiledAllToAll.schedule``) to its ppermute groups under the
    compiler's fusion rule.  Pure host-side; the result is static data
    baked into the traced program."""
    if not schedule:
        raise ValueError("dispatch_plan needs at least one round")
    n = schedule[0].size
    rounds = []
    for r in schedule:
        pairs = [p for cls in r.shift_classes for p in cls.perm]
        srcs = [s for s, _ in pairs]
        dsts = [d for _, d in pairs]
        if (len(set(srcs)) == len(srcs)
                and len(set(dsts)) == len(dsts)):
            groups = (tuple(sorted(pairs)),)
        else:
            groups = tuple(tuple(cls.perm) for cls in r.shift_classes)
        rounds.append(groups)
    return DispatchPlan(n=n, rounds=tuple(rounds))


def _group_tables(group: Sequence[Pair],
                  n: int) -> Tuple[np.ndarray, np.ndarray]:
    """Static send/recv tables of one ppermute group: ``send[r]`` is
    rank r's destination (-1 when r does not send this group),
    ``recv[r]`` the rank whose shard lands here (-1 when none)."""
    send = np.full((n,), -1, np.int32)
    recv = np.full((n,), -1, np.int32)
    for s, d in group:
        send[s] = d
        recv[d] = s
    return send, recv


def all_to_all_dispatch(x: jax.Array, plan: DispatchPlan,
                        axis_name: str,
                        wire_dtype: Optional[str] = None) -> jax.Array:
    """Run the compiled all-to-all: ``x[d]`` is this rank's shard
    addressed to rank ``d`` (leading axis ``n``); the result's slot
    ``s`` holds the shard rank ``s`` addressed here.  The self shard
    never touches the wire.

    ``wire_dtype="int8"`` compresses each permute's payload with a
    per-group absmax int8 code (scale rides a second scalar permute) —
    the lossy wire the determinism tests exercise; the byte-for-byte
    HLO contract is stated for the default full-precision wire.
    """
    if wire_dtype not in (None, "int8"):
        raise ValueError(f"unknown wire_dtype {wire_dtype!r}")
    n = plan.n
    me = lax.axis_index(axis_name)
    y = jnp.zeros_like(x)
    y = y.at[me].set(x[me])
    zero_slot = jnp.zeros_like(x[0])
    for groups in plan.rounds:
        for group in groups:
            send, recv = _group_tables(group, n)
            dst = jnp.asarray(send)[me]
            src = jnp.asarray(recv)[me]
            payload = jnp.where(dst >= 0, x[jnp.clip(dst, 0, n - 1)],
                                zero_slot)
            perm = [(int(s), int(d)) for s, d in group]
            if wire_dtype == "int8":
                scale = jnp.max(jnp.abs(payload)) / 127.0
                scale = jnp.where(scale > 0, scale,
                                  jnp.ones_like(scale))
                q = jnp.clip(jnp.round(payload / scale), -127,
                             127).astype(jnp.int8)
                q = lax.ppermute(q, axis_name, perm)
                s_in = lax.ppermute(scale, axis_name, perm)
                out = q.astype(x.dtype) * s_in.astype(x.dtype)
            else:
                out = lax.ppermute(payload, axis_name, perm)
            y = y.at[jnp.clip(src, 0, n - 1)].add(
                jnp.where(src >= 0, out, jnp.zeros_like(out)))
    return y


def naive_all_to_all(x: jax.Array, axis_name: str) -> jax.Array:
    """The baseline the bench bills: XLA's own ``lax.all_to_all`` over
    the shard axis — semantically identical to
    :func:`all_to_all_dispatch` (tested), topology-blind on the wire."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)


# ------------------------------------------------------------------ #
# expert placement + traced resilience data
# ------------------------------------------------------------------ #
def expert_owner(rank: int, n_experts: int) -> int:
    """Which expert a rank hosts: round-robin, so expert ``e``'s
    replica set is every rank ``r`` with ``r % n_experts == e``."""
    return rank % n_experts


def default_route_table(n: int, n_experts: int) -> np.ndarray:
    """``[n, n_experts] int32``: the replica of expert ``e`` that rank
    ``src`` dispatches to — sources fan out round-robin across the
    expert's replicas, so no replica is a hot spot by construction."""
    if not 1 <= n_experts <= n:
        raise ValueError(
            f"need 1 <= n_experts <= n, got {n_experts} experts on "
            f"{n} ranks")
    route = np.zeros((n, n_experts), np.int32)
    for e in range(n_experts):
        replicas = [r for r in range(n) if r % n_experts == e]
        for src in range(n):
            route[src, e] = replicas[src % len(replicas)]
    return route


def heal_route_table(route, dead_mask, n_experts: int) -> np.ndarray:
    """Reroute every dispatch entry pointing at a dead rank to a
    surviving replica of the same expert (round-robin over survivors —
    the dead rank's load spreads instead of piling onto one neighbor).
    Host-side and shape-preserving: the healed table is the SAME
    ``[n, n_experts]`` traced operand, so swapping it in never
    recompiles.  An expert with no surviving replica is unroutable —
    that is a capacity loss no reroute can paper over, so it raises."""
    route = np.array(route, np.int32, copy=True)
    n = route.shape[0]
    dead = np.asarray(dead_mask, bool).reshape(n)
    for e in range(n_experts):
        live = [r for r in range(n)
                if r % n_experts == e and not dead[r]]
        if not live:
            raise ValueError(
                f"expert {e} has no surviving replica — cannot heal")
        k = 0
        for src in range(n):
            if dead[route[src, e]]:
                route[src, e] = live[k % len(live)]
                k += 1
    if dead.any():
        from bluefog_tpu.observe import blackbox as _blackbox

        _blackbox.record_decision(
            "moe", "replan", step=-1,
            telemetry={"dead": [int(i) for i in np.flatnonzero(dead)],
                       "n_experts": int(n_experts), "size": int(n)})
    return route


def capacity_mask_of(dead_mask) -> np.ndarray:
    """``[n] float32``: 1.0 for ranks accepting expert traffic, 0.0
    for dead ones — the traced multiplier that zeroes contributions
    from (and to) dead slots without touching the wire schedule."""
    dead = np.asarray(dead_mask, bool).reshape(-1)
    return (1.0 - dead.astype(np.float32)).astype(np.float32)
