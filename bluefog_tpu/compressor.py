"""Gradient compression.

Capability parity with the reference's compressor prototype
(reference compressor/Compressor.py: TopKCompressor, RandomKCompressor,
QuantizedCompressor; compressor/CompressedOptimizer.py wrapper).

TPU-first redesign: XLA has no sparse tensors and wants static shapes, so a
compressed gradient is a **dense array with all but k entries zeroed**
(``lax.top_k`` + scatter) — the communication saving on TPU comes from
sending the compact ``(values, indices)`` pair when paired with an
allgather, or simply from the sparsity pattern when the combine is local.
Compressors are pure functions (explicit PRNG keys), so they compose with
jit/shard_map; :func:`compress_gradients` wraps any of them as an optax
gradient transformation, the functional twin of the reference's
CompressedOptimizer.

There is ONE top-k kernel and ONE k-resolution rule in the repo:
:func:`topk_mask_encode` / :func:`topk_mask_decode` (with
:func:`_resolve_k` for the k/percentage contract) back both the eager
gradient compressors here AND the error-feedback compressed parameter
mixing (``parallel.collectives.mix_compress_exchange``, selected via
``build_train_step(compress="topk")``) — parity between the two paths
is asserted in tests/test_compressor.py.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "TopKCompressor",
    "RandomKCompressor",
    "QuantizedCompressor",
    "compress_gradients",
    "CompressedOptimizer",
    "topk_mask_encode",
    "topk_mask_decode",
]


def _resolve_k(k: Optional[int], percentage: Optional[float], numel: int) -> int:
    """Reference argument contract (Compressor.py:16-27)."""
    if k is None and percentage is None:
        raise ValueError("At least one of 'k' or 'percentage' must be provided")
    if k is not None and percentage is not None:
        raise ValueError("The 'k' and 'percentage' parameters are mutually exclusive.")
    if percentage is not None:
        if percentage < 0 or percentage > 1:
            raise ValueError("'percentage' must be a float number between 0 and 1")
        return max(int(percentage * numel), 1)
    if int(k) <= 0:
        raise ValueError(f"'k' must be a positive int, got {k}")
    return min(int(k), numel)


def topk_mask_encode(flat: jax.Array, k: int, k_live=None):
    """THE top-k kernel — shared by the eager gradient compressors and
    the compressed-mixing wire (``collectives.mix_compress_exchange``).

    Selects the ``k`` largest-magnitude entries of the flat ``[n]``
    vector and returns ``(mask, vals)``:

    * ``mask`` — boolean ``[n]`` keep-mask (the wire ships it packed,
      8 entries/byte);
    * ``vals`` — ``[k]`` kept values in ASCENDING-INDEX order, zeros
      beyond the live count — exactly the order
      :func:`topk_mask_decode`'s cumsum reconstruction consumes, so
      sender and receiver rebuild the identical dense delta bitwise.

    ``k_live`` (optional, may be a TRACED int32 scalar ``<= k``)
    tightens the kept count at runtime without changing any shape: the
    control plane's online compression-ratio knob rides it, so a ratio
    swap is pure data — zero recompiles.  Selection is ``lax.top_k``
    (ties resolve to the lowest index, deterministically); dropped
    candidates are routed to out-of-range sentinel positions so the
    position sort never collides with kept entries.
    """
    n = flat.shape[0]
    _, idx = jax.lax.top_k(jnp.abs(flat), k)
    live = jnp.arange(k) < (k if k_live is None else k_live)
    pos = jnp.where(live, idx, n + jnp.arange(k))
    pos = jnp.sort(pos)
    valid = pos < n
    safe = jnp.where(valid, pos, 0)
    vals = jnp.where(valid, flat[safe], jnp.zeros((), flat.dtype))
    # scatter-ADD of the valid flags (not set): dropped entries clamp to
    # position 0, and a duplicate-index set would nondeterministically
    # clobber a kept True there — addition is order-free
    mask = jnp.zeros((n,), jnp.int32).at[safe].add(
        valid.astype(jnp.int32)) > 0
    return mask, vals


def topk_mask_decode(mask: jax.Array, vals: jax.Array) -> jax.Array:
    """Dense ``[n]`` vector from a keep-mask plus ascending-index
    values — the inverse of :func:`topk_mask_encode`.  Pure gather
    (``cumsum(mask) - 1`` ranks each kept position among the kept set),
    so the same ``(mask, vals)`` pair decodes bitwise-identically on
    sender and receiver — the consistency the error-feedback mixing
    state depends on."""
    cum = jnp.cumsum(mask.astype(jnp.int32)) - 1
    safe = jnp.clip(cum, 0, vals.shape[0] - 1)
    return jnp.where(mask, vals[safe], jnp.zeros((), vals.dtype))


class TopKCompressor:
    """Keep the k largest-magnitude entries, zero the rest (dense)."""

    def __init__(self, *, k: Optional[int] = None,
                 percentage: Optional[float] = None):
        _resolve_k(k, percentage, 1 << 30)  # validate eagerly
        self.k = k
        self.percentage = percentage

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        flat = x.reshape(-1)
        kk = _resolve_k(self.k, self.percentage, flat.size)
        out = topk_mask_decode(*topk_mask_encode(flat, kk))
        return out.reshape(x.shape)


class RandomKCompressor:
    """Keep k uniformly-random entries, zero the rest (dense)."""

    def __init__(self, *, k: Optional[int] = None,
                 percentage: Optional[float] = None):
        _resolve_k(k, percentage, 1 << 30)
        self.k = k
        self.percentage = percentage

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        if key is None:
            raise ValueError("RandomKCompressor needs an explicit PRNG key")
        flat = x.reshape(-1)
        kk = _resolve_k(self.k, self.percentage, flat.size)
        idx = jax.random.choice(key, flat.size, shape=(kk,), replace=False)
        out = jnp.zeros_like(flat).at[idx].set(flat[idx])
        return out.reshape(x.shape)


class QuantizedCompressor:
    """QSGD-style stochastic quantization to s levels
    (reference Compressor.py:80-108)."""

    def __init__(self, s: int):
        self.s = int(s)

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        if key is None:
            raise ValueError("QuantizedCompressor needs an explicit PRNG key")
        flat = x.reshape(-1).astype(jnp.float32)
        norm = jnp.max(jnp.abs(flat))
        safe_norm = jnp.where(norm == 0, 1.0, norm)
        scale = jnp.abs(flat) / safe_norm * self.s
        lower = jnp.clip(jnp.floor(scale), 0, self.s - 1)
        p = scale - lower
        bump = (jax.random.uniform(key, flat.shape) < p).astype(jnp.float32)
        level = lower + bump
        out = norm * jnp.sign(flat) * level / self.s
        return out.reshape(x.shape).astype(x.dtype)


class _CompressState(NamedTuple):
    count: jnp.ndarray  # int32 step counter -> per-step PRNG keys


def compress_gradients(compressor, seed: int = 0) -> optax.GradientTransformation:
    """Optax transformation applying ``compressor`` to every gradient leaf —
    chain it before the base optimizer, the functional equivalent of the
    reference's CompressedOptimizer (CompressedOptimizer.py:9-23)::

        opt = optax.chain(compress_gradients(TopKCompressor(k=10)),
                          optax.sgd(0.1))
    """
    base_key = jax.random.PRNGKey(seed)

    def init_fn(params):
        del params
        return _CompressState(count=jnp.zeros((), jnp.int32))

    def update_fn(updates, state, params=None):
        del params
        step_key = jax.random.fold_in(base_key, state.count)
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        keys = jax.random.split(step_key, max(len(leaves), 1))
        new_leaves = [
            compressor(leaf, key=keys[i]) for i, leaf in enumerate(leaves)
        ]
        return (jax.tree_util.tree_unflatten(treedef, new_leaves),
                _CompressState(count=state.count + 1))

    return optax.GradientTransformation(init_fn, update_fn)


def CompressedOptimizer(base_optimizer: optax.GradientTransformation,
                        compressor, seed: int = 0) -> optax.GradientTransformation:
    """Name-parity factory (reference CompressedOptimizer.py:24-28)."""
    return optax.chain(compress_gradients(compressor, seed), base_optimizer)
