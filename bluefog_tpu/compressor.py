"""Gradient compression.

Capability parity with the reference's compressor prototype
(reference compressor/Compressor.py: TopKCompressor, RandomKCompressor,
QuantizedCompressor; compressor/CompressedOptimizer.py wrapper).

TPU-first redesign: XLA has no sparse tensors and wants static shapes, so a
compressed gradient is a **dense array with all but k entries zeroed**
(``lax.top_k`` + scatter) — the communication saving on TPU comes from
sending the compact ``(values, indices)`` pair when paired with an
allgather, or simply from the sparsity pattern when the combine is local.
Compressors are pure functions (explicit PRNG keys), so they compose with
jit/shard_map; :func:`compress_gradients` wraps any of them as an optax
gradient transformation, the functional twin of the reference's
CompressedOptimizer.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import optax

__all__ = [
    "TopKCompressor",
    "RandomKCompressor",
    "QuantizedCompressor",
    "compress_gradients",
    "CompressedOptimizer",
]


def _resolve_k(k: Optional[int], percentage: Optional[float], numel: int) -> int:
    """Reference argument contract (Compressor.py:16-27)."""
    if k is None and percentage is None:
        raise ValueError("At least one of 'k' or 'percentage' must be provided")
    if k is not None and percentage is not None:
        raise ValueError("The 'k' and 'percentage' parameters are mutually exclusive.")
    if percentage is not None:
        if percentage < 0 or percentage > 1:
            raise ValueError("'percentage' must be a float number between 0 and 1")
        return max(int(percentage * numel), 1)
    if int(k) <= 0:
        raise ValueError(f"'k' must be a positive int, got {k}")
    return min(int(k), numel)


class TopKCompressor:
    """Keep the k largest-magnitude entries, zero the rest (dense)."""

    def __init__(self, *, k: Optional[int] = None,
                 percentage: Optional[float] = None):
        _resolve_k(k, percentage, 1 << 30)  # validate eagerly
        self.k = k
        self.percentage = percentage

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        flat = x.reshape(-1)
        kk = _resolve_k(self.k, self.percentage, flat.size)
        _, idx = jax.lax.top_k(jnp.abs(flat), kk)
        out = jnp.zeros_like(flat).at[idx].set(flat[idx])
        return out.reshape(x.shape)


class RandomKCompressor:
    """Keep k uniformly-random entries, zero the rest (dense)."""

    def __init__(self, *, k: Optional[int] = None,
                 percentage: Optional[float] = None):
        _resolve_k(k, percentage, 1 << 30)
        self.k = k
        self.percentage = percentage

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        if key is None:
            raise ValueError("RandomKCompressor needs an explicit PRNG key")
        flat = x.reshape(-1)
        kk = _resolve_k(self.k, self.percentage, flat.size)
        idx = jax.random.choice(key, flat.size, shape=(kk,), replace=False)
        out = jnp.zeros_like(flat).at[idx].set(flat[idx])
        return out.reshape(x.shape)


class QuantizedCompressor:
    """QSGD-style stochastic quantization to s levels
    (reference Compressor.py:80-108)."""

    def __init__(self, s: int):
        self.s = int(s)

    def __call__(self, x: jax.Array, key=None) -> jax.Array:
        if key is None:
            raise ValueError("QuantizedCompressor needs an explicit PRNG key")
        flat = x.reshape(-1).astype(jnp.float32)
        norm = jnp.max(jnp.abs(flat))
        safe_norm = jnp.where(norm == 0, 1.0, norm)
        scale = jnp.abs(flat) / safe_norm * self.s
        lower = jnp.clip(jnp.floor(scale), 0, self.s - 1)
        p = scale - lower
        bump = (jax.random.uniform(key, flat.shape) < p).astype(jnp.float32)
        level = lower + bump
        out = norm * jnp.sign(flat) * level / self.s
        return out.reshape(x.shape).astype(x.dtype)


class _CompressState(NamedTuple):
    count: jnp.ndarray  # int32 step counter -> per-step PRNG keys


def compress_gradients(compressor, seed: int = 0) -> optax.GradientTransformation:
    """Optax transformation applying ``compressor`` to every gradient leaf —
    chain it before the base optimizer, the functional equivalent of the
    reference's CompressedOptimizer (CompressedOptimizer.py:9-23)::

        opt = optax.chain(compress_gradients(TopKCompressor(k=10)),
                          optax.sgd(0.1))
    """
    base_key = jax.random.PRNGKey(seed)

    def init_fn(params):
        del params
        return _CompressState(count=jnp.zeros((), jnp.int32))

    def update_fn(updates, state, params=None):
        del params
        step_key = jax.random.fold_in(base_key, state.count)
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        keys = jax.random.split(step_key, max(len(leaves), 1))
        new_leaves = [
            compressor(leaf, key=keys[i]) for i, leaf in enumerate(leaves)
        ]
        return (jax.tree_util.tree_unflatten(treedef, new_leaves),
                _CompressState(count=state.count + 1))

    return optax.GradientTransformation(init_fn, update_fn)


def CompressedOptimizer(base_optimizer: optax.GradientTransformation,
                        compressor, seed: int = 0) -> optax.GradientTransformation:
    """Name-parity factory (reference CompressedOptimizer.py:24-28)."""
    return optax.chain(compress_gradients(compressor, seed), base_optimizer)
