"""Weight-only int8 quantization for HBM-bound decode.

Decode streams every parameter once per emitted token, so bytes are
time: int8 kernels halve the traffic vs bf16 (quarter vs f32) and RAISE
the analytic tokens/s ceiling by the same factor.  This module converts
a trained full-precision Llama param tree into the layout
``QuantDense`` (models/llama.py) consumes: each projection kernel
``W [.., in, out]`` becomes ``round(W / s)`` in int8 with one f32 scale
per output channel ``s = max(|W|, axis=in) / 127``.  Per-output-channel
scaling is exact through the matmul (``x @ (W_q * s) == (x @ W_q) * s``)
— the only rounding is the int8 snap itself, ~0.4% RMS per weight.

The reference framework is training-only (SURVEY.md §2: no inference
stack); this is part of the beyond-parity generation path
(models/generate.py).

Usage::

    qvars = quantize_llama_params(variables)   # once, offline
    out = llama_generate(qvars, cfg, prompt, n, weight_quant="int8")
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["quantize_llama_params", "is_quantized_params",
           "QUANT_KERNELS"]

# Modules whose "kernel" param quantizes: all seven projection kernels
# plus the logits head.  Embeddings stay full precision — decode gathers
# one row per token, so their HBM traffic is negligible; norm scales are
# vectors.  MoE expert tensors are excluded because cached decode does
# not support MoE (models/generate.py).
QUANT_KERNELS = ("wq", "wk", "wv", "wo", "w1", "w2", "w3", "output")


def _quantize_kernel(w: jax.Array):
    """int8 kernel + per-output-channel f32 scale for ``w [.., in, out]``
    (a leading scan-layer axis quantizes per layer automatically: the
    reduction is over the input axis only)."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -127, 127)
    return q.astype(jnp.int8), scale


def quantize_llama_params(variables):
    """Convert a trained Llama param tree to the ``param_quant='int8'``
    layout.

    Accepts either ``{"params": tree}`` (as returned by ``model.init`` /
    the HF importer) or the bare param tree, and returns the same
    structure with every ``{"kernel": W}`` under a :data:`QUANT_KERNELS`
    module replaced by ``{"kernel": int8, "scale": f32[out]}``.  Works
    for both unrolled (``layer_i/...``) and scanned (``layers/block``)
    layouts — the scale reduction is over the input axis only, so a
    leading ``[n_layers]`` axis yields per-layer scales, matching what
    ``nn.scan`` expects for the per-layer ``scale`` param.
    """
    wrapped = isinstance(variables, dict) and "params" in variables
    params = variables["params"] if wrapped else variables

    def walk(tree):
        out = {}
        for name, sub in tree.items():
            if isinstance(sub, dict):
                if name in QUANT_KERNELS and set(sub) == {"kernel"}:
                    q, scale = _quantize_kernel(sub["kernel"])
                    out[name] = {"kernel": q, "scale": scale}
                else:
                    out[name] = walk(sub)
            else:
                out[name] = sub
        return out

    qparams = walk(dict(params))
    if wrapped:
        out = dict(variables)
        out["params"] = qparams
        return out
    return qparams


def is_quantized_params(variables) -> bool:
    """True if the tree already carries the int8 layout (any
    :data:`QUANT_KERNELS` module with both ``kernel`` and ``scale``)."""
    params = variables.get("params", variables) \
        if isinstance(variables, dict) else variables
    found = [False]

    def walk(tree):
        for name, sub in tree.items():
            if isinstance(sub, dict):
                if name in QUANT_KERNELS and "scale" in sub \
                        and "kernel" in sub:
                    found[0] = True
                    return
                walk(sub)

    walk(params)
    return found[0]
