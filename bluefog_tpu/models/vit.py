"""Vision Transformer, TPU-first.

Rounds out the vision side of the model zoo next to ResNet (the reference
benchmarks torchvision models only — reference examples/pytorch_resnet.py:54;
ViT is the modern equivalent workload).  Fresh flax.linen implementation:
bf16 compute over f32 params, NHWC patchify via a strided conv (one MXU-
friendly matmul per image), learned position embeddings, pre-LN blocks.

``attn_impl='flash'`` routes token attention through the Pallas flash
kernel (non-causal); ``attn_mode='blockwise'`` gives the VMEM-bounded XLA
path for very long token sequences.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
from flax import linen as nn

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
)


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    dim: int = 768
    depth: int = 12
    n_heads: int = 12
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16
    attn_mode: str = "full"  # full | blockwise
    attn_impl: str = "xla"  # xla | flash (Pallas)
    attn_block_size: int = 256
    pool: str = "cls"  # cls | gap
    # Learned register tokens (Darcet et al., "Vision Transformers Need
    # Registers") appended after the patch+cls sequence; their outputs are
    # discarded before pooling.  "auto" adds exactly enough to make the
    # token count 8-aligned — the default 224/16+cls geometry gives t=197
    # (prime), which Mosaic would otherwise have to tile as a
    # non-8-aligned Pallas block.  The count depends ONLY on the token
    # geometry, never on attn_impl, so the parameter tree is identical
    # across the xla/flash/blockwise implementations (a flash-trained
    # checkpoint evaluates bit-compatibly on the xla path).
    # Round-3 verification on real-TPU Mosaic (v5e): n_register_tokens=0
    # (t=197, non-8-aligned block) compiles AND matches the xla path to
    # bf16 tolerance — "auto" remains the default purely as the faster
    # tiling, not a correctness requirement.
    n_register_tokens: object = "auto"  # int | "auto"

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def small(**overrides) -> "ViTConfig":
        return ViTConfig(dim=384, depth=12, n_heads=6, **overrides)

    @staticmethod
    def base(**overrides) -> "ViTConfig":
        return ViTConfig(dim=768, depth=12, n_heads=12, **overrides)

    @staticmethod
    def tiny(**overrides) -> "ViTConfig":
        """Test-scale config."""
        return ViTConfig(image_size=32, patch_size=8, num_classes=10,
                         dim=64, depth=2, n_heads=4, **overrides)


class _Attention(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, dtype=cfg.dtype, param_dtype=jnp.float32, name=name)
        q = dense(cfg.dim, "wq")(x).reshape(b, t, cfg.n_heads, hd)
        k = dense(cfg.dim, "wk")(x).reshape(b, t, cfg.n_heads, hd)
        v = dense(cfg.dim, "wv")(x).reshape(b, t, cfg.n_heads, hd)
        if cfg.attn_impl == "flash":
            from bluefog_tpu.parallel.pallas_attention import flash_attention

            out = flash_attention(q, k, v, causal=False,
                                  block_q=min(cfg.attn_block_size, t),
                                  block_k=min(cfg.attn_block_size, t))
        elif cfg.attn_mode == "blockwise":
            out = blockwise_attention(q, k, v, cfg.attn_block_size,
                                      causal=False)
        else:
            out = full_attention(q, k, v, causal=False)
        return dense(cfg.dim, "wo")(out.reshape(b, t, cfg.dim))


class _Block(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        ln = lambda name: nn.LayerNorm(dtype=cfg.dtype,
                                       param_dtype=jnp.float32, name=name)
        x = x + _Attention(cfg, name="attn")(ln("norm1")(x))
        h = ln("norm2")(x)
        h = nn.Dense(cfg.dim * cfg.mlp_ratio, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="mlp_in")(h)
        h = nn.gelu(h)
        h = nn.Dense(cfg.dim, dtype=cfg.dtype, param_dtype=jnp.float32,
                     name="mlp_out")(h)
        return x + h


class ViT(nn.Module):
    """images [B, H, W, 3] -> logits [B, num_classes] (f32)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg = self.cfg
        b = images.shape[0]
        x = nn.Conv(cfg.dim, (cfg.patch_size, cfg.patch_size),
                    strides=(cfg.patch_size, cfg.patch_size),
                    dtype=cfg.dtype, param_dtype=jnp.float32,
                    name="patch_embed")(images.astype(cfg.dtype))
        x = x.reshape(b, -1, cfg.dim)  # [B, T, dim]
        t = x.shape[1]
        if cfg.pool == "cls":
            cls = self.param("cls_token", nn.initializers.zeros,
                             (1, 1, cfg.dim), jnp.float32)
            x = jnp.concatenate(
                [jnp.broadcast_to(cls, (b, 1, cfg.dim)).astype(cfg.dtype), x],
                axis=1)
            t += 1
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, t, cfg.dim), jnp.float32)
        x = x + pos.astype(cfg.dtype)
        if cfg.n_register_tokens == "auto":
            n_reg = (-t) % 8
        else:
            n_reg = int(cfg.n_register_tokens)
        if n_reg:
            reg = self.param("reg_tokens",
                             nn.initializers.normal(stddev=0.02),
                             (1, n_reg, cfg.dim), jnp.float32)
            x = jnp.concatenate(
                [x, jnp.broadcast_to(reg, (b, n_reg, cfg.dim)).astype(
                    cfg.dtype)], axis=1)
        for i in range(cfg.depth):
            x = _Block(cfg, name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=cfg.dtype, param_dtype=jnp.float32,
                         name="norm")(x)
        if n_reg:
            x = x[:, :t]  # registers are working memory, not outputs
        x = x[:, 0] if cfg.pool == "cls" else jnp.mean(x, axis=1)
        return nn.Dense(cfg.num_classes, dtype=jnp.float32,
                        param_dtype=jnp.float32, name="head")(x)


def ViT_S16(**overrides) -> ViT:
    return ViT(ViTConfig.small(**overrides))


def ViT_B16(**overrides) -> ViT:
    return ViT(ViTConfig.base(**overrides))
