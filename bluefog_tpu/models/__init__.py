"""Model zoo for BlueFog-TPU.

The reference trains torchvision models (reference examples/pytorch_resnet.py:54,
examples/pytorch_benchmark.py) and a small MNIST CNN (reference
examples/pytorch_mnist.py:125-143).  These are TPU-first flax.linen
re-designs: NHWC layouts, bf16 compute with f32 params, static shapes so XLA
tiles every conv/matmul onto the MXU.
"""

from bluefog_tpu.models.mlp import MLP, MnistNet
from bluefog_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from bluefog_tpu.models.llama import (
    Llama,
    LlamaConfig,
    chunked_xent,
    llama_chunked_xent_loss_fn,
    llama_circular_layout,
    llama_param_specs,
    llama_pp_loss_fn,
    vocab_parallel_xent,
)
from bluefog_tpu.models.generate import init_cache, llama_generate
from bluefog_tpu.models.quant import quantize_llama_params
from bluefog_tpu.models.vit import ViT, ViTConfig, ViT_B16, ViT_S16

__all__ = [
    "ViT",
    "ViTConfig",
    "ViT_S16",
    "ViT_B16",
    "MLP",
    "MnistNet",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "Llama",
    "LlamaConfig",
    "llama_param_specs",
    "llama_pp_loss_fn",
    "chunked_xent",
    "llama_chunked_xent_loss_fn",
    "llama_circular_layout",
    "llama_generate",
    "init_cache",
    "quantize_llama_params",
    "vocab_parallel_xent",
]
