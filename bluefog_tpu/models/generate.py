"""Autoregressive generation with K/V caching.

The reference framework is training-only; users of an LLM framework also
need inference.  This is the TPU-native decode loop: one prefill call
writes the prompt's K/V into per-layer caches (flax "cache" collection),
then a single ``lax.scan`` emits tokens one at a time — the whole
generation is jittable (static prompt length / token budget / cache
size), with no per-token host round trips beyond the final fetch.

Trained parameters decode directly: ``decode=True`` changes no param
shapes (``LlamaConfig.decode``), and both layer layouts (unrolled and
``scan_layers``) carry caches (the scanned stack declares a cache axis).
"""

from __future__ import annotations

import dataclasses
import functools
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bluefog_tpu.models.llama import Llama, LlamaConfig

__all__ = ["init_cache", "llama_generate", "decode_config",
           "prefill_cache", "decode_token_step", "verify_window"]


def _decode_cfg(cfg: LlamaConfig, max_len: int, keep_tp: bool = False,
                kv_quant: str = "none",
                weight_quant: str = "none",
                decode_attn: str = "xla") -> LlamaConfig:
    """Decode layout: sequence/expert mesh knobs are cleared (they are
    training-time layouts); tensor parallelism is KEPT when requested —
    a tp-sharded K/V-cached decode serves checkpoints too big for one
    chip (each shard holds its own heads' cache; outputs merge through
    the same f/g psum pair as training).

    MoE configs decode with DROPLESS routing (capacity_factor raised to
    n_experts, so per-group capacity >= group_tokens * top_k): train-
    time capacity drops depend on which tokens are co-batched, so a
    cached one-token-at-a-time decode could never reproduce them —
    dropless routing removes the coupling entirely (each token always
    gets its full top-k combine, making the output grouping-invariant),
    and the cached decode matches the dropless full forward
    token-for-token (tests/test_moe_decode.py).  This is the standard
    inference treatment: capacity is a training-throughput knob, not
    part of the learned function.  The training config's
    ``moe_group_size`` is KEPT: grouped dropless routing is exact too,
    and it is what keeps the prefill's dispatch/combine tensors linear
    in the prompt length."""
    moe = {}
    if cfg.n_experts:
        if cfg.moe_router != "topk":
            raise NotImplementedError(
                "llama_generate supports only moe_router='topk' "
                "(expert_choice is non-causal and cannot decode)")
        moe = dict(capacity_factor=max(cfg.capacity_factor,
                                       float(cfg.n_experts)))
    if decode_attn == "auto":
        # measured dispatch (decode_*_r05.json): the fused Pallas step
        # wins only on full-precision caches at short context; int8
        # caches and 2k+ positions belong to the XLA lowering.  The
        # kernel also needs a viable S tiling (>=8-row block divisor) —
        # awkward cache lengths fall back to XLA instead of erroring —
        # and a REAL TPU: off-TPU the kernel would run in Pallas
        # interpret mode, orders of magnitude slower than the einsums.
        from bluefog_tpu.parallel.pallas_decode import _fit_block

        viable = max_len < 8 or _fit_block(max_len, 512) >= 8
        decode_attn = ("pallas" if kv_quant == "none" and max_len <= 1024
                       and viable and jax.default_backend() == "tpu"
                       else "xla")
    tp = {} if keep_tp else {"tp_axis": None, "tp_size": 1}
    # vocab_parallel is a training-time memory layout (it shards the
    # optimizer-state-bearing vocab matrices); decode clears it like the
    # other training-only knobs — the param TREE is identical, so a
    # vocab_parallel-trained checkpoint serves through the replicated
    # head directly.
    return dataclasses.replace(
        cfg, decode=True, max_seq_len=max_len, attn_mode="full",
        attn_impl="xla", sp_axis=None, ep_axis=None, ep_size=1,
        remat=False, remat_policy="none", kv_quant=kv_quant,
        param_quant=weight_quant, decode_attn=decode_attn,
        vocab_parallel=False, tp_seq_shard=False, **moe, **tp)


def decode_config(cfg: LlamaConfig, max_len: int, *, keep_tp: bool = False,
                  kv_quant: str = "none", weight_quant: str = "none",
                  decode_attn: str = "xla") -> LlamaConfig:
    """Public form of the decode-layout transform: the config a K/V-cached
    decode program runs under (``decode=True``, cache length ``max_len``,
    training-time mesh knobs cleared; see :func:`_decode_cfg`).  The
    serving engine (``bluefog_tpu.serving``) builds its resident model
    from this, so engine steps and :func:`llama_generate` share one
    definition of "the decode layout" — and therefore one numerics."""
    return _decode_cfg(cfg, max_len, keep_tp=keep_tp, kv_quant=kv_quant,
                       weight_quant=weight_quant, decode_attn=decode_attn)


def prefill_cache(model: Llama, params, cache, tokens: jax.Array):
    """Cache-writing prefill: one multi-token forward writes ``tokens``'s
    K/V into ``cache`` at its current index.  Returns ``(logits, cache')``
    with ``logits [B, T, V]``.  ``params`` is the bare param tree (not the
    ``{"params": ...}`` wrapper).  Shared by :func:`llama_generate`'s
    one-shot path and the serving engine's chunked prefill — both are
    this exact call, so their numerics agree token for token."""
    logits, mut = model.apply({"params": params, "cache": cache}, tokens,
                              mutable=["cache"])
    return logits, mut["cache"]


def decode_token_step(model: Llama, params, cache, tok: jax.Array):
    """One incremental decode step: append ``tok [B, 1]``'s K/V and return
    ``(last_logits [B, V], cache')``.  The single-token twin of
    :func:`prefill_cache`, shared by the one-shot scan body and the
    serving engine's slot-batched step."""
    logits, mut = model.apply({"params": params, "cache": cache}, tok,
                              mutable=["cache"])
    return logits[:, -1], mut["cache"]


def verify_window(model: Llama, params, cache, tokens: jax.Array):
    """Multi-token cached forward that keeps EVERY position's logits:
    append ``tokens [B, T]``'s K/V (exactly like :func:`prefill_cache`)
    and return ``(logits [B, T, V], cache')``.  This is speculative
    decoding's verify step — one target forward scores a whole draft
    window, so position *i*'s logits give the target distribution after
    ``tokens[:, :i+1]`` and acceptance/rejection is decided without T
    separate decode steps.  Cache writes are identical to
    ``prefill_cache``'s, so a verify window and a chunked prefill leave
    the same K/V behind."""
    logits, mut = model.apply({"params": params, "cache": cache}, tokens,
                              all_logits=True, mutable=["cache"])
    return logits, mut["cache"]


def init_cache(cfg: LlamaConfig, batch_size: int, max_len: int,
               keep_tp: bool = False, kv_quant: str = "none"):
    """Zero K/V caches for ``batch_size`` sequences of up to ``max_len``
    tokens — built from shapes only (``jax.eval_shape``), no forward
    pass, no params needed.  With ``keep_tp`` the shapes are PER-SHARD
    (local kv heads) for the tp-sharded decode path; ``kv_quant='int8'``
    yields the int8 + per-vector-scale cache layout."""
    model = Llama(_decode_cfg(cfg, max_len, keep_tp=keep_tp,
                              kv_quant=kv_quant))
    shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((batch_size, 1), jnp.int32)))
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                        shapes["cache"])


def llama_generate(variables, cfg: LlamaConfig, prompt: jax.Array,
                   max_new_tokens: int, *, temperature: float = 0.0,
                   rng: Optional[jax.Array] = None,
                   max_len: Optional[int] = None,
                   mesh=None, kv_quant: str = "none",
                   weight_quant: str = "none",
                   decode_attn: str = "auto",
                   eos_id: Optional[int] = None) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt``.

    Args:
      variables: ``{"params": ...}`` from training / HF import (any
        layer layout; model-parallel shardings are the caller's concern —
        pass replicated params here).
      cfg: the model's config (its ``decode``/``max_seq_len`` are
        overridden internally).
      prompt: ``[B, T_prompt]`` int32 token ids.
      max_new_tokens: number of tokens to emit (static, >= 1).
      temperature: 0 = greedy argmax; otherwise softmax sampling at this
        temperature (needs ``rng``).  Traced — changing the temperature
        does NOT recompile (only switching greedy <-> sampling does).
      max_len: cache length; defaults to ``T_prompt + max_new_tokens``.
      kv_quant: "int8" stores the K/V cache as int8 with per-vector f32
        scales — half the cache HBM traffic (decode is bandwidth-bound).
      weight_quant: "int8" (weight-only) or "w8a8" (also quantizes
        activations per token and runs native s8xs8 MXU dots) run every
        projection + the logits head from int8 kernels with
        per-output-channel scales.  The faster mode is SCALE-DEPENDENT
        (measured, docs/performance.md round 4): "w8a8" wins at ~200M
        (the weight-only convert path is VPU-bound there), "int8" wins
        at ~1B+ (larger contractions amortize the convert and w8a8's
        activation-quant overhead flips the ordering) — benchmark both
        with examples/decode_benchmark.py.  ``variables`` must already
        be the quantized tree
        (:func:`bluefog_tpu.models.quant.quantize_llama_params` — do it
        once offline, not per call).
      decode_attn: "pallas" runs single-token decode steps through the
        fused Pallas attention kernel (one launch per layer, in-kernel
        int8 cache dequant, float probabilities —
        parallel/pallas_decode.py); "xla" keeps the einsum lowering;
        "auto" (default) picks by the measured boundary — pallas for
        full-precision caches up to 1024 positions (+13%/+6%/+3% at
        200M B8/B32/1B), xla for int8 caches and long context
        (decode_*_r05.json).  Measure: examples/decode_benchmark.py
        ``--decode-attn``.
      eos_id: early-stop token id.  Once a row emits ``eos_id`` its
        remaining positions are frozen to ``eos_id`` (the done mask rides
        the ``lax.scan`` carry, so finished rows stop emitting sampled
        tokens); rows that never emit it are bit-identical to the
        unstopped path.  ``None`` (default) disables the check.  Static:
        switching eos ids compiles a new program (one id per served
        model in practice).

    Returns ``[B, T_prompt + max_new_tokens]`` int32: prompt ‖ generation.
    """
    if max_new_tokens < 1:
        raise ValueError(f"max_new_tokens ({max_new_tokens}) must be >= 1")
    b, t_prompt = prompt.shape
    total = t_prompt + max_new_tokens
    max_len = max_len or total
    if max_len < total:
        raise ValueError(f"max_len ({max_len}) < prompt + new tokens "
                         f"({total})")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs rng=")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    from bluefog_tpu.models.quant import is_quantized_params

    if (weight_quant != "none") != is_quantized_params(variables):
        raise ValueError(
            "weight_quant='int8'/'w8a8' requires params converted by "
            "quantize_llama_params (and full-precision params require "
            "weight_quant='none'); got a mismatched tree")
    quant = dict(kv_quant=kv_quant, weight_quant=weight_quant,
                 decode_attn=decode_attn)
    if cfg.tp_size > 1 and mesh is not None:
        # tp-sharded decode: run the whole generate program under
        # shard_map over the tp axis — params shard by the Megatron
        # column/row layout, each shard keeps its own heads' K/V cache,
        # and the psum-merged logits are replicated so every shard
        # samples the same token (same rng).  Without mesh= the tp knobs
        # are cleared and decode runs replicated (the original
        # single-chip behavior).
        dcfg = _decode_cfg(cfg, max_len, keep_tp=True, **quant)
        fn = _tp_generate_program(dcfg, max_new_tokens,
                                  temperature == 0.0, max_len, mesh,
                                  eos_id)
        return fn(variables["params"], prompt, jnp.float32(temperature),
                  rng)
    return _generate_impl(
        variables, prompt, jnp.float32(temperature), rng,
        cfg=_decode_cfg(cfg, max_len, **quant),
        max_new_tokens=max_new_tokens,
        greedy=temperature == 0.0, max_len=max_len, eos_id=eos_id)


def _generate_body(variables, prompt, temperature, rng, *,
                   cfg: LlamaConfig, max_new_tokens: int, greedy: bool,
                   max_len: int,
                   eos_id: Optional[int] = None) -> jax.Array:
    b = prompt.shape[0]
    model = Llama(cfg)
    params = variables["params"]
    # cfg here is already the decode layout; keep_tp preserves its tp
    # knobs so the cache shapes are per-shard under the tp shard_map
    cache = init_cache(cfg, b, max_len, keep_tp=cfg.tp_size > 1,
                       kv_quant=cfg.kv_quant)

    def sample(logits_last, rng):
        if greedy:
            return jnp.argmax(logits_last, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            rng, logits_last / temperature, axis=-1).astype(jnp.int32)

    # prefill: one multi-token call writes the prompt K/V
    logits, cache = prefill_cache(model, params, cache, prompt)
    rng, sub = jax.random.split(rng)
    tok = sample(logits[:, -1], sub)

    def step(carry, _):
        cache, tok, rng, done = carry
        last, cache = decode_token_step(model, params, cache, tok[:, None])
        rng, sub = jax.random.split(rng)
        nxt = sample(last, sub)
        if eos_id is not None:
            # a row is done once it has EMITTED eos; its later positions
            # freeze to eos_id (the already-emitted tok passes through
            # untouched — the first eos itself is part of the output)
            done = done | (tok == eos_id)
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
        return (cache, nxt, rng, done), tok

    done0 = jnp.zeros((b,), bool)
    (_, last, _, _), toks = lax.scan(step, (cache, tok, rng, done0), None,
                                     length=max_new_tokens - 1)
    generated = jnp.concatenate(
        [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1) \
        if max_new_tokens > 1 else tok[:, None]
    return jnp.concatenate([prompt, generated], axis=1)


_generate_impl = partial(jax.jit, static_argnames=(
    "cfg", "max_new_tokens", "greedy", "max_len", "eos_id"))(_generate_body)


@functools.lru_cache(maxsize=8)
def _tp_generate_program(dcfg: LlamaConfig, max_new_tokens: int,
                         greedy: bool, max_len: int, mesh,
                         eos_id: Optional[int] = None):
    """Cached jitted shard_map program for tp-sharded decode — a serving
    loop reuses ONE compilation per (config, token budget, mesh).  The
    param partition specs derive from the config alone (via eval_shape),
    so the cache key never needs the concrete params."""
    from jax.sharding import PartitionSpec as P

    from bluefog_tpu.models.llama import llama_param_specs

    # structure-only init of the tp-CLEARED twin (identical param paths
    # and ranks — including QuantDense's scale leaves, so weight_quant
    # carries over; tracing the tp model outside shard_map would hit
    # unbound-axis psums)
    plain = _decode_cfg(dcfg, dcfg.max_seq_len,
                        weight_quant=dcfg.param_quant)
    abstract = jax.eval_shape(
        lambda: Llama(plain).init(jax.random.PRNGKey(0),
                                  jnp.zeros((1, 1), jnp.int32)))
    pspecs = llama_param_specs(abstract["params"], rank_axis=None,
                               tp_axis=dcfg.tp_axis, ep_axis=None)

    def body(params, prompt, temperature, rng):
        return _generate_body(
            {"params": params}, prompt, temperature, rng, cfg=dcfg,
            max_new_tokens=max_new_tokens, greedy=greedy, max_len=max_len,
            eos_id=eos_id)

    sm = jax.shard_map(body, mesh=mesh, in_specs=(pspecs, P(), P(), P()),
                       out_specs=P(), check_vma=False)
    return jax.jit(sm)
