"""Small dense / conv models for MNIST-scale experiments.

Parity targets: the reference MNIST CNN (reference examples/pytorch_mnist.py:
125-143 — two 5x5 convs with max-pool + dropout + two dense layers) and the
linear models used by the optimizer convergence tests (reference
test/torch_optimizer_test.py:100 LinearProblemBuilder).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
from flax import linen as nn


class MLP(nn.Module):
    """Plain MLP: features[i] hidden widths, final layer logits."""

    features: Sequence[int] = (128, 64, 10)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for width in self.features[:-1]:
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Dense(self.features[-1], dtype=self.dtype)(x)


class MnistNet(nn.Module):
    """The reference's MNIST CNN re-done in NHWC (reference
    examples/pytorch_mnist.py:125-143): conv(10,5x5) -> pool -> conv(20,5x5)
    -> pool -> dense(50) -> dense(10).  Dropout is omitted from the default
    path (deterministic flag controls it) so the jitted step stays pure.
    """

    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        # x: [N, 28, 28, 1] NHWC (TPU-native layout).
        x = x.astype(self.dtype)
        x = nn.Conv(10, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = nn.Conv(20, (5, 5), padding="VALID", dtype=self.dtype)(x)
        x = nn.max_pool(nn.relu(x), (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(50, dtype=self.dtype)(x))
        if not deterministic:
            x = nn.Dropout(0.5, deterministic=False)(x)
        return nn.Dense(10, dtype=self.dtype)(x)
