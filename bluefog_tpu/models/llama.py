"""Llama-style decoder-only transformer, TPU-first.

Capability target: BASELINE.json's "Llama-3-8B decentralized SGD with
neighbor_allreduce" stress config.  Fresh flax.linen implementation —
RMSNorm + rotary embeddings + grouped-query attention + SwiGLU — designed
for the MXU (bf16 compute, f32 params, static shapes) and for sequence
parallelism: ``attn_mode='ring'`` shards the sequence over a mesh axis and
runs :func:`bluefog_tpu.parallel.ring_attention.ring_attention`, making
long-context first-class (the reference has none — SURVEY.md §5).

The module itself never touches the mesh; under ``shard_map`` the caller
passes ``pos_offset = axis_index * T_local`` so rotary phases line up across
sequence shards.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
    ring_attention,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: Optional[int] = None  # default 8/3 * dim rounded to 256
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    attn_mode: str = "full"  # full | blockwise | ring
    attn_impl: str = "xla"  # xla | flash (Pallas kernel; composes with
    #                         attn_mode="ring" incl. training — the ring
    #                         VJP re-runs the Pallas bwd per ring step)
    attn_block_size: int = 512  # for blockwise mode
    sp_axis: Optional[str] = None  # mesh axis for ring mode
    # Tensor (Megatron-style) parallelism: heads + FFN hidden sharded over
    # ``tp_axis`` (``tp_size`` shards, static).  Column-parallel kernels
    # (wq/wk/wv/w1/w3) shard their output dim, row-parallel ones (wo/w2)
    # their input dim with one psum each per block; activations stay
    # replicated over tp.  The param TREE is identical to tp_size=1 (the
    # global kernels keep full logical shapes — sharding happens in the
    # PartitionSpecs, see ``llama_param_specs``), so checkpoints move
    # freely between TP layouts.  A capability beyond the reference
    # (SURVEY.md §2.3: TP absent there).
    tp_axis: Optional[str] = None
    tp_size: int = 1
    remat: bool = False
    # Compile the decoder stack as ONE nn.scan'd block instead of L unrolled
    # copies: params gain a leading [n_layers] axis, trace/compile time goes
    # O(L) -> O(1), and remat composes per scan step (the standard TPU
    # recipe for deep LLMs; the reference has no analogue — torch eager
    # re-executes Python per layer).
    scan_layers: bool = False
    remat_policy: str = "none"  # none | dots | everything (with remat)
    # Final logits matmul precision (MaxText's logits_dot_in_fp32): True
    # runs the [*, dim] x [dim, vocab] head in f32 (stablest; the
    # default), False runs it in the compute dtype with the logits cast
    # to f32 afterwards — ~2x faster head at bf16-rounded logits.
    logits_dot_in_fp32: bool = True

    def __post_init__(self):
        valid = ("none", "dots", "everything")
        if self.remat_policy not in valid:
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in {valid}")
        if self.remat_policy != "none" and not self.remat:
            raise ValueError("remat_policy requires remat=True")
        if self.tp_size > 1:
            if self.tp_axis is None:
                raise ValueError("tp_size > 1 requires tp_axis")
            for name, val in (("n_heads", self.n_heads),
                              ("n_kv_heads", self.n_kv_heads),
                              ("ffn_dim", self.ffn_dim)):
                if val % self.tp_size:
                    raise ValueError(
                        f"{name} ({val}) must divide by tp_size "
                        f"({self.tp_size})")

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.hidden_dim is not None:
            return self.hidden_dim
        h = int(8 * self.dim / 3)
        return ((h + 255) // 256) * 256

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, rope_theta=500000.0, **overrides)

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-scale config."""
        return LlamaConfig(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq_len=256, **overrides)


class RMSNorm(nn.Module):
    eps: float = 1e-5

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


def rotary_embed(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Apply rotary position embedding.  x: [B, T, H, D], positions: [T]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    out = jnp.stack([out1, out2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


# --------------------------------------------------------------------- #
# Megatron's conjugate communication operators.  Under shard_map every tp
# shard computes an IDENTICAL copy of the loss and differentiates it with
# seed 1, so the raw lax.psum is wrong in reverse (its transpose is
# another psum: sharded-kernel cotangents get multiplied by tp_size and
# the activation cotangent entering a parallel region is left partial).
# The fix is the f/g pair from the Megatron-LM paper:
#   f: identity forward, psum backward  (enter a parallel region)
#   g: psum forward, identity backward  (leave a parallel region)
# With them, TP gradients equal the unsharded model's exactly
# (tests/test_tp.py::test_tp_gradients_match_single_shard).
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_in(x, axis_name):
    return x


def _tp_region_in_fwd(x, axis_name):
    return x, None


def _tp_region_in_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


_tp_region_in.defvjp(_tp_region_in_fwd, _tp_region_in_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_out(x, axis_name):
    return jax.lax.psum(x, axis_name)


def _tp_region_out_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _tp_region_out_bwd(axis_name, _, g):
    return (g,)


_tp_region_out.defvjp(_tp_region_out_fwd, _tp_region_out_bwd)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        cfg = self.cfg
        b, t, _ = x.shape
        hd = cfg.head_dim
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name)
        # under TP this module runs per-shard: local head counts; wo's
        # partial output is psum'd below (Megatron column->row pattern,
        # entered through the 'f' operator so the backward is exact)
        tp = cfg.tp_axis is not None and cfg.tp_size > 1
        if tp:
            x = _tp_region_in(x, cfg.tp_axis)
        n_q = cfg.n_heads // cfg.tp_size
        n_kv = cfg.n_kv_heads // cfg.tp_size
        q = dense(n_q * hd, "wq")(x).reshape(b, t, n_q, hd)
        k = dense(n_kv * hd, "wk")(x).reshape(b, t, n_kv, hd)
        v = dense(n_kv * hd, "wv")(x).reshape(b, t, n_kv, hd)
        positions = pos_offset + jnp.arange(t)
        q = rotary_embed(q, positions, cfg.rope_theta)
        k = rotary_embed(k, positions, cfg.rope_theta)
        if cfg.attn_mode == "ring":
            assert cfg.sp_axis is not None, "ring attention needs sp_axis"
            out = ring_attention(q, k, v, cfg.sp_axis, causal=True,
                                 impl=cfg.attn_impl)
        elif cfg.attn_impl == "flash":
            from bluefog_tpu.parallel.pallas_attention import flash_attention

            out = flash_attention(q, k, v, causal=True,
                                  block_q=min(cfg.attn_block_size, t),
                                  block_k=min(cfg.attn_block_size, t))
        elif cfg.attn_mode == "blockwise":
            out = blockwise_attention(q, k, v, cfg.attn_block_size, causal=True)
        else:
            out = full_attention(q, k, v, causal=True)
        out = out.reshape(b, t, n_q * hd)
        proj = dense(cfg.dim, "wo")(out)
        if tp:
            proj = _tp_region_out(proj, cfg.tp_axis)
        return proj


class FeedForward(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(
            feats, use_bias=False, dtype=cfg.dtype, param_dtype=jnp.float32,
            name=name)
        tp = cfg.tp_axis is not None and cfg.tp_size > 1
        if tp:
            x = _tp_region_in(x, cfg.tp_axis)
        local_ffn = cfg.ffn_dim // cfg.tp_size
        gate = dense(local_ffn, "w1")(x)
        up = dense(local_ffn, "w3")(x)
        down = dense(cfg.dim, "w2")(nn.silu(gate) * up)
        if tp:
            down = _tp_region_out(down, cfg.tp_axis)
        return down


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        x = x + Attention(self.cfg, name="attention")(
            RMSNorm(self.cfg.norm_eps, name="attention_norm")(x), pos_offset)
        x = x + FeedForward(self.cfg, name="feed_forward")(
            RMSNorm(self.cfg.norm_eps, name="ffn_norm")(x))
        return x


class _ScanBlock(nn.Module):
    """nn.scan adapter: Block with a (carry, out) return signature."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        return Block(self.cfg, name="block")(x, pos_offset), None


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, pos_offset=0):
        """tokens: [B, T_local] int32 -> logits [B, T_local, vocab] f32."""
        cfg = self.cfg
        assert tokens.shape[1] <= cfg.max_seq_len, (
            f"sequence shard {tokens.shape[1]} exceeds max_seq_len "
            f"{cfg.max_seq_len}")
        x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="tok_embeddings")(tokens)
        policies = {
            "none": None,
            "dots": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
            "everything": jax.checkpoint_policies.nothing_saveable,
        }
        policy = policies[cfg.remat_policy]
        if cfg.scan_layers:
            # one compiled block, scanned n_layers times; params get a
            # leading [n_layers] axis under "layers" — trace/compile cost
            # stops growing with depth
            body = _ScanBlock
            if cfg.remat:
                # prevent_cse=False: XLA's loop lowering already blocks the
                # problematic CSE under scan; the default True would insert
                # an opt-barrier per scanned layer
                body = nn.checkpoint(body, static_argnums=(), policy=policy,
                                     prevent_cse=False)
            scan_cls = nn.scan(
                body,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.n_layers,
                metadata_params={nn.meta.PARTITION_NAME: None},
            )
            x, _ = scan_cls(cfg, name="layers")(x, pos_offset)
        else:
            block_cls = Block
            if cfg.remat:
                block_cls = nn.checkpoint(Block, static_argnums=(),
                                          policy=policy)
            for i in range(cfg.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(x, pos_offset)
        x = RMSNorm(cfg.norm_eps, name="norm")(x)
        head_dtype = jnp.float32 if cfg.logits_dot_in_fp32 else cfg.dtype
        logits = nn.Dense(cfg.vocab_size, use_bias=False, dtype=head_dtype,
                          param_dtype=jnp.float32, name="output")(x)
        return logits.astype(jnp.float32)


def llama_param_specs(params_or_shapes, rank_axis: str = "bf",
                      tp_axis: str = "tp"):
    """PartitionSpec tree for rank-major Llama params under tensor
    parallelism: column-parallel kernels (wq/wk/wv/w1/w3) shard their
    OUTPUT (last) dim over ``tp_axis``, row-parallel kernels (wo/w2)
    their INPUT (second-to-last) dim; embeddings, norms, and the logits
    head stay replicated.  Works for both unrolled and scanned layouts
    (the kernel rank decides where the sharded dim sits).  Feed the
    result to ``optim.functional.build_train_step(param_specs=...)``."""
    from jax.sharding import PartitionSpec as P

    column = ("wq", "wk", "wv", "w1", "w3")
    row = ("wo", "w2")

    def spec_for(path, leaf):
        names = "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        # leaf shapes come WITHOUT the leading rank axis (pass the tree
        # that model.init returned); the produced specs are for the
        # rank-major global arrays, so the rank axis is prepended here
        nd = len(leaf.shape)
        if any(f"/{k}/" in f"/{names}/" for k in column) and nd >= 2:
            return P(rank_axis, *([None] * (nd - 1)), tp_axis)
        if any(f"/{k}/" in f"/{names}/" for k in row) and nd >= 2:
            return P(rank_axis, *([None] * (nd - 2)), tp_axis, None)
        return P(rank_axis)

    return jax.tree_util.tree_map_with_path(spec_for, params_or_shapes)
