"""Llama-style decoder-only transformer, TPU-first.

Capability target: BASELINE.json's "Llama-3-8B decentralized SGD with
neighbor_allreduce" stress config.  Fresh flax.linen implementation —
RMSNorm + rotary embeddings + grouped-query attention + SwiGLU — designed
for the MXU (bf16 compute, f32 params, static shapes) and for sequence
parallelism: ``attn_mode='ring'`` shards the sequence over a mesh axis and
runs :func:`bluefog_tpu.parallel.ring_attention.ring_attention`, making
long-context first-class (the reference has none — SURVEY.md §5).

The module itself never touches the mesh; under ``shard_map`` the caller
passes ``pos_offset = axis_index * T_local`` so rotary phases line up across
sequence shards.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from flax import linen as nn

from bluefog_tpu.parallel.ring_attention import (
    blockwise_attention,
    full_attention,
    ring_attention,
)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    hidden_dim: Optional[int] = None  # default 8/3 * dim rounded to 256
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: jnp.dtype = jnp.bfloat16
    attn_mode: str = "full"  # full | blockwise | ring | ulysses
    attn_impl: str = "xla"  # xla | flash (Pallas kernel; composes with
    #                         attn_mode="ring" incl. training — the ring
    #                         VJP re-runs the Pallas bwd per ring step)
    #                         | splash (library fused-bwd kernel; plain
    #                         causal full-sequence train path only —
    #                         +10% measured end-to-end tokens/s at
    #                         200M/1B, parallel/splash.py)
    attn_block_size: int = 512  # for blockwise/ring/ulysses modes
    # Llama-3.1-style rope scaling (HF rope_type='llama3'): "none" or
    # "llama3".  Flat fields keep the config hashable (it is a jit
    # static argument); reference semantics in _llama3_scaled_freqs.
    rope_scaling_kind: str = "none"  # none | llama3
    rope_scaling_factor: float = 8.0
    rope_scaling_low_freq_factor: float = 1.0
    rope_scaling_high_freq_factor: float = 4.0
    rope_scaling_original_max_len: int = 8192
    # Tile sizes for the full-sequence Pallas flash kernel (q tile /
    # k tile; both clamped to t).  Measured on v5e (round 3): 1024 q
    # tiles beat 512 by +18% tokens/s at 200M and +13% at 1B end-to-end.
    # Round 5 added causal BLOCK SKIPPING (fully-masked k blocks execute
    # nothing, pallas_attention._block_live), which flips the k-tile
    # optimum: a k block spanning the whole sequence never skips, while
    # 1024-wide k blocks skip a quarter of the grid at seq 2048 —
    # re-measured end-to-end, q1024/k1024 beats the round-3 q1024/k2048
    # at BOTH 200M (+1.9%) and 1B (+2.3%).  The backward pass
    # auto-shrinks its q tile to keep its two score-sized f32
    # intermediates inside the 16 MB scoped VMEM (_flash_bwd_impl).
    attn_flash_block_size: int = 1024
    attn_flash_block_k: int = 1024
    sp_axis: Optional[str] = None  # mesh axis for ring mode
    # Tensor (Megatron-style) parallelism: heads + FFN hidden sharded over
    # ``tp_axis`` (``tp_size`` shards, static).  Column-parallel kernels
    # (wq/wk/wv/w1/w3) shard their output dim, row-parallel ones (wo/w2)
    # their input dim with one psum each per block; activations stay
    # replicated over tp.  The param TREE is identical to tp_size=1 (the
    # global kernels keep full logical shapes — sharding happens in the
    # PartitionSpecs, see ``llama_param_specs``), so checkpoints move
    # freely between TP layouts.  A capability beyond the reference
    # (SURVEY.md §2.3: TP absent there).
    tp_axis: Optional[str] = None
    tp_size: int = 1
    # Mixture-of-Experts FFN with expert parallelism (Mixtral-style;
    # another capability past the reference's DP-only scope).
    # ``n_experts > 0`` replaces the dense FFN with ``moe_top_k``-routed
    # experts; experts shard over ``ep_axis`` (``ep_size`` shards), each
    # shard evaluating its local experts on the replicated token stream
    # and the outputs merging through ONE psum per layer (the same f/g
    # conjugate pair as TP keeps the backward exact).  Static capacity
    # ``capacity_factor * tokens * top_k / n_experts`` per expert keeps
    # shapes XLA-friendly; overflow tokens fall through the residual.
    n_experts: int = 0
    moe_top_k: int = 2
    ep_axis: Optional[str] = None
    ep_size: int = 1
    capacity_factor: float = 1.25
    # Routing group size: tokens are routed within fixed-size groups with
    # per-group expert capacity (flaxformer/MaxText-style), keeping the
    # dispatch/combine tensors O(s * group) instead of O(s^2) — without
    # grouping, capacity grows with s and the [s, E, cap] one-hots blow
    # up at benchmark sequence lengths.  0 = one group over all tokens
    # (exact original behavior); otherwise the effective group is the
    # largest divisor of the token count <= this value.
    moe_group_size: int = 4096
    # Router flavor: "topk" (token-choice, autoregressive-safe, the
    # Mixtral/Switch default) or "expert_choice" (each expert takes its
    # top-capacity tokens per group — dropless and perfectly balanced by
    # construction, but NOT causal; for encoder/bidirectional stacks).
    moe_router: str = "topk"
    # Expert-choice routing conditions each token's expert assignment on
    # the OTHER tokens in its group — including future ones — so on this
    # causal decoder stack train-time logits are not reproducible
    # autoregressively.  Selecting it requires this explicit
    # acknowledgement (e.g. for representation learning, distillation
    # teachers, or ablations where autoregressive deployment is not the
    # goal); otherwise __post_init__ refuses the combination.
    allow_noncausal_router: bool = False
    # Weight of the Switch-style load-balance auxiliary loss.  The loss
    # is always sown under "intermediates" (scan included); the shipped
    # loss builders (llama_benchmark, llama_pp_loss_fn) ADD
    # moe_aux_weight * total_aux to the objective when this is > 0 —
    # without it routers can collapse onto few experts and capacity
    # drops silently bypass the FFN.
    moe_aux_weight: float = 0.0
    remat: bool = False
    # Compile the decoder stack as ONE nn.scan'd block instead of L unrolled
    # copies: params gain a leading [n_layers] axis, trace/compile time goes
    # O(L) -> O(1), and remat composes per scan step (the standard TPU
    # recipe for deep LLMs; the reference has no analogue — torch eager
    # re-executes Python per layer).
    scan_layers: bool = False
    remat_policy: str = "none"  # none | dots | everything (with remat)
    # Autoregressive decoding: attention layers keep [B, max_seq_len]
    # K/V caches (flax "cache" collection) and attend incrementally —
    # see models/generate.py.  Training configs leave this False; the
    # param tree is identical either way, so trained params decode
    # directly.
    decode: bool = False
    # Final logits matmul precision (MaxText's logits_dot_in_fp32): True
    # runs the [*, dim] x [dim, vocab] head in f32 (stablest; the
    # default), False runs it in the compute dtype with the logits cast
    # to f32 afterwards — ~2x faster head at bf16-rounded logits.
    logits_dot_in_fp32: bool = True
    # Inference-time quantization (decode is HBM-bound: every step
    # streams all params + the K/V cache once, so bytes ARE time).
    # kv_quant="int8": the decode K/V caches store int8 with one f32
    # scale per (batch, kv_head, position) vector; both scales commute
    # out of the attention contractions (over head_dim for scores, over
    # positions via the probabilities for values), so dequantization
    # fuses into the matmul operand reads and HBM traffic halves.
    # param_quant="int8": every projection kernel (wq/wk/wv/wo/w1/w2/w3
    # and the logits head) stores int8 with a per-output-channel f32
    # scale applied to the matmul OUTPUT ((x @ W_q) * s == x @ (W_q * s)
    # exactly, since s is constant along the contraction) — see
    # QuantDense.  Both are decode-only knobs (set via llama_generate);
    # training stays full precision.
    kv_quant: str = "none"  # none | int8
    param_quant: str = "none"  # none | int8
    # decode_attn="pallas": single-token decode steps run the fused
    # Pallas attention kernel (parallel/pallas_decode.py: one launch per
    # layer, in-kernel int8 cache dequant, probabilities kept float).
    # "xla" keeps the einsum lowering.  Measured (r05, decode_*_r05
    # artifacts): pallas wins on FULL-PRECISION caches at short context
    # (+13% at 200M B8, +6% B32, +3% at 1B), loses ~5% on int8 caches
    # (its in-kernel int8->f32 convert vs XLA's fused dequant) and ~2x
    # at 2k+ cache positions.  ``llama_generate(decode_attn="auto")``
    # dispatches on exactly that boundary.  Prefill (t > 1) always XLA.
    decode_attn: str = "xla"  # xla | pallas
    # Megatron-style vocab parallelism: the token embedding shards its
    # VOCAB rows and the logits head its VOCAB columns over ``tp_axis``,
    # so the two [128k x 4096] matrices stop being replicated per chip —
    # at Llama-3-8B scale they are ~4.2 GB of f32 params per chip (plus
    # the same again in momentum and gradients), the difference between
    # fitting a 16 GB v5e chip and not (benchmarks/llama_8b_structural).
    # The model then RETURNS VOCAB-SHARDED logits [B, T, V/tp]; train
    # with ``vocab_parallel_xent`` (exact vocab-parallel cross-entropy,
    # one pmax + two psums per step).  Training-only: decode keeps the
    # replicated head (no optimizer state there to dominate memory).
    vocab_parallel: bool = False
    # Megatron sequence-parallel ACTIVATIONS (their "sequence
    # parallelism" paper, distinct from ring/Ulysses attention SP): the
    # residual stream, norms, and remat-saved layer boundaries live
    # SEQ-SHARDED [B, T/tp, D] per chip; entering a tp region
    # all-gathers the rows and leaving it reduce-scatters them (the
    # conjugate pair _sp_region_in/_sp_region_out — same total bytes as
    # the f/g identity/psum pair, but activation memory divides by tp).
    # At 8B this is what lets an 8-CHIP tp group fit 16 GB v5e HBM
    # (benchmarks/llama_8b_structural.py).  Training-only; composes
    # with vocab_parallel (the head re-gathers rows once).
    tp_seq_shard: bool = False

    def __post_init__(self):
        if self.decode and self.attn_mode != "full":
            raise ValueError(
                f"decode=True requires attn_mode='full' (got "
                f"{self.attn_mode!r}); incremental K/V caching and "
                "ring/blockwise attention do not compose")
        if self.decode and self.n_experts:
            # capacity-dropped routing depends on how many tokens are
            # processed together, so a cached decode (one token at a
            # time) could not reproduce a capacity-dropped forward
            # token-for-token.  DROPLESS routing removes the coupling:
            # with per-group capacity >= group_tokens * top_k
            # (capacity_factor >= n_experts — exact for ANY group
            # size), every token gets its full top-k combine no matter
            # what it is co-batched with, so the cached decode matches
            # the dropless full forward exactly
            # (tests/test_moe_decode.py).  llama_generate raises the
            # capacity automatically; grouping stays as configured (it
            # keeps prefill dispatch memory linear in prompt length).
            if self.moe_router != "topk":
                raise ValueError(
                    "decode=True supports only moe_router='topk' "
                    "(expert_choice is non-causal)")
            if self.capacity_factor < self.n_experts:
                raise ValueError(
                    "decode=True with MoE requires DROPLESS routing: "
                    "capacity_factor >= n_experts (per-group capacity "
                    ">= group_tokens * top_k), so the cached "
                    "one-token-at-a-time decode reproduces the "
                    "dropless forward exactly — llama_generate "
                    "configures this automatically")
        if self.kv_quant not in ("none", "int8"):
            raise ValueError(
                f"kv_quant {self.kv_quant!r} not in ('none', 'int8')")
        if self.param_quant not in ("none", "int8", "w8a8"):
            raise ValueError(
                f"param_quant {self.param_quant!r} not in "
                "('none', 'int8', 'w8a8')")
        if self.kv_quant != "none" and not self.decode:
            raise ValueError(
                "kv_quant is a decode-time knob (it shapes the K/V cache "
                "layout); training/eval forward passes have no cache — "
                "set it through llama_generate")
        if self.param_quant != "none" and not self.decode:
            raise ValueError(
                "param_quant is inference-only (int8 kernels are not "
                "differentiable); set it through llama_generate and "
                "convert params with quantize_llama_params")
        if self.attn_impl not in ("xla", "flash", "splash"):
            raise ValueError(
                f"attn_impl {self.attn_impl!r} not in "
                "('xla', 'flash', 'splash')")
        if self.attn_impl == "splash":
            if self.attn_mode != "full":
                raise ValueError(
                    "attn_impl='splash' serves the plain full-sequence "
                    "causal path only (no LSE output to merge across "
                    "ring/ulysses steps) — use attn_impl='flash' with "
                    f"attn_mode={self.attn_mode!r}")
            if self.decode:
                raise ValueError(
                    "attn_impl='splash' is a train-time knob; decode "
                    "uses decode_attn ('xla' | 'pallas')")
        if self.decode_attn not in ("xla", "pallas"):
            raise ValueError(
                f"decode_attn {self.decode_attn!r} not in "
                "('xla', 'pallas')")
        if self.decode_attn == "pallas" and not self.decode:
            raise ValueError(
                "decode_attn='pallas' is a decode-time knob (the fused "
                "kernel serves single-token cached steps); set it "
                "through llama_generate")
        if self.vocab_parallel:
            if self.tp_size <= 1 or self.tp_axis is None:
                raise ValueError("vocab_parallel requires tensor "
                                 "parallelism (tp_axis + tp_size > 1)")
            if self.vocab_size % self.tp_size:
                raise ValueError(
                    f"vocab_size ({self.vocab_size}) must divide by "
                    f"tp_size ({self.tp_size}) for vocab_parallel")
            if self.decode:
                raise ValueError(
                    "vocab_parallel is a training-time memory layout "
                    "(it shards the optimizer-state-bearing vocab "
                    "matrices); decode keeps the replicated head — drop "
                    "vocab_parallel from the decode config")
        if self.tp_seq_shard:
            if self.tp_size <= 1 or self.tp_axis is None:
                raise ValueError("tp_seq_shard requires tensor "
                                 "parallelism (tp_axis + tp_size > 1)")
            if self.decode:
                raise ValueError(
                    "tp_seq_shard is a training-time activation layout; "
                    "drop it from the decode config (llama_generate "
                    "does this automatically)")
            if self.n_experts:
                raise ValueError(
                    "tp_seq_shard + MoE is not supported (experts use "
                    "the ep region operators; MoE configs exclude tp "
                    "anyway)")
            if self.attn_mode in ("ring", "ulysses"):
                raise ValueError(
                    "tp_seq_shard already shards the sequence over tp; "
                    "composing it with ring/ulysses attention "
                    "(sp_axis) is redundant — pick one")
            if not self.vocab_parallel:
                raise ValueError(
                    "tp_seq_shard requires vocab_parallel=True: a "
                    "REPLICATED logits head consumed by seq-sharded "
                    "rows would get per-shard partial gradients (each "
                    "shard only sees its own rows), while the "
                    "vocab-parallel head re-gathers the rows once and "
                    "stays exact — and at the scales where "
                    "tp_seq_shard matters the vocab matrices dominate "
                    "memory anyway")
        if self.rope_scaling_kind not in ("none", "llama3"):
            raise ValueError(
                f"rope_scaling_kind {self.rope_scaling_kind!r} not in "
                "('none', 'llama3')")
        valid = ("none", "dots", "everything")
        if self.remat_policy not in valid:
            raise ValueError(
                f"remat_policy {self.remat_policy!r} not in {valid}")
        if self.remat_policy != "none" and not self.remat:
            raise ValueError("remat_policy requires remat=True")
        if self.tp_size > 1:
            if self.tp_axis is None:
                raise ValueError("tp_size > 1 requires tp_axis")
            for name, val in (("n_heads", self.n_heads),
                              ("n_kv_heads", self.n_kv_heads),
                              ("ffn_dim", self.ffn_dim)):
                if val % self.tp_size:
                    raise ValueError(
                        f"{name} ({val}) must divide by tp_size "
                        f"({self.tp_size})")
        if self.ep_size > 1:
            if self.ep_axis is None:
                raise ValueError("ep_size > 1 requires ep_axis")
            if not self.n_experts:
                raise ValueError("ep_size > 1 requires n_experts > 0")
        if self.moe_router not in ("topk", "expert_choice"):
            raise ValueError(f"moe_router {self.moe_router!r} not in "
                             "('topk', 'expert_choice')")
        if self.moe_router == "expert_choice" \
                and not self.allow_noncausal_router:
            raise ValueError(
                "moe_router='expert_choice' is non-causal (each token's "
                "routing depends on later tokens in its group) but this "
                "stack is a causal decoder: trained logits would not be "
                "reproducible autoregressively.  Pass "
                "allow_noncausal_router=True to acknowledge this "
                "explicitly, or use moe_router='topk'.")
        if self.n_experts:
            if self.n_experts % self.ep_size:
                raise ValueError(
                    f"n_experts ({self.n_experts}) must divide by ep_size "
                    f"({self.ep_size})")
            if self.moe_top_k > self.n_experts:
                raise ValueError("moe_top_k exceeds n_experts")
            if self.tp_size > 1:
                raise ValueError(
                    "MoE + tensor parallelism in one config is not "
                    "supported yet (experts are not tp-sharded)")

    @property
    def rope_scaling(self):
        """The ``rotary_embed`` scaling tuple, or None when disabled."""
        if self.rope_scaling_kind == "none":
            return None
        return (self.rope_scaling_factor,
                self.rope_scaling_low_freq_factor,
                self.rope_scaling_high_freq_factor,
                self.rope_scaling_original_max_len)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def ffn_dim(self) -> int:
        if self.hidden_dim is not None:
            return self.hidden_dim
        h = int(8 * self.dim / 3)
        return ((h + 255) // 256) * 256

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, rope_theta=500000.0, **overrides)

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-scale config."""
        base = dict(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, hidden_dim=128, max_seq_len=256)
        base.update(overrides)
        return LlamaConfig(**base)


def _remat_policies():
    return {
        "none": None,
        "dots": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
        "everything": jax.checkpoint_policies.nothing_saveable,
    }


class RMSNorm(nn.Module):
    eps: float = 1e-5
    # tp_seq_shard: the scale is a REPLICATED param consumed by
    # seq-sharded rows, so its per-shard gradient is partial (each
    # shard only sees its own rows); routing the param through the f
    # operator (identity forward, psum backward) restores the full
    # gradient on every shard — Megatron all-reduces its layernorm
    # grads across the tp group for exactly this reason.
    grad_psum_axis: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        if self.grad_psum_axis is not None:
            scale = _tp_region_in(scale, self.grad_psum_axis)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(x.dtype)


def _llama3_scaled_freqs(freqs: jax.Array, factor: float,
                         low_freq_factor: float, high_freq_factor: float,
                         original_max_len: int) -> jax.Array:
    """Llama-3.1's ``rope_type='llama3'`` frequency scaling (the HF
    implementation's piecewise rule): wavelengths shorter than the
    high-freq cutoff keep their frequency, longer than the low-freq
    cutoff divide by ``factor``, and the band between interpolates
    smoothly — long-context extension without hurting local attention."""
    low_wavelen = original_max_len / low_freq_factor
    high_wavelen = original_max_len / high_freq_factor
    wavelen = 2.0 * jnp.pi / freqs
    smooth = (original_max_len / wavelen - low_freq_factor) / (
        high_freq_factor - low_freq_factor)
    smooth = jnp.clip(smooth, 0.0, 1.0)
    interp = (1.0 - smooth) * freqs / factor + smooth * freqs
    return jnp.where(
        wavelen < high_wavelen, freqs,
        jnp.where(wavelen > low_wavelen, freqs / factor, interp))


def rotary_embed(x: jax.Array, positions: jax.Array, theta: float,
                 scaling=None) -> jax.Array:
    """Apply rotary position embedding.  x: [B, T, H, D], positions: [T].
    ``scaling``: optional ``(factor, low_freq_factor, high_freq_factor,
    original_max_len)`` tuple enabling llama3-style frequency scaling."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    if scaling is not None:
        freqs = _llama3_scaled_freqs(freqs, *scaling)
    angles = positions.astype(jnp.float32)[:, None] * freqs[None, :]  # [T, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    out1 = x1 * cos - x2 * sin
    out2 = x1 * sin + x2 * cos
    out = jnp.stack([out1, out2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


# --------------------------------------------------------------------- #
# Megatron's conjugate communication operators.  Under shard_map every tp
# shard computes an IDENTICAL copy of the loss and differentiates it with
# seed 1, so the raw lax.psum is wrong in reverse (its transpose is
# another psum: sharded-kernel cotangents get multiplied by tp_size and
# the activation cotangent entering a parallel region is left partial).
# The fix is the f/g pair from the Megatron-LM paper:
#   f: identity forward, psum backward  (enter a parallel region)
#   g: psum forward, identity backward  (leave a parallel region)
# With them, TP gradients equal the unsharded model's exactly
# (tests/test_tp.py::test_tp_gradients_match_single_shard).
# --------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_in(x, axis_name):
    return x


def _tp_region_in_fwd(x, axis_name):
    return x, None


def _tp_region_in_bwd(axis_name, _, g):
    return (jax.lax.psum(g, axis_name),)


_tp_region_in.defvjp(_tp_region_in_fwd, _tp_region_in_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _tp_region_out(x, axis_name):
    return jax.lax.psum(x, axis_name)


def _tp_region_out_fwd(x, axis_name):
    return jax.lax.psum(x, axis_name), None


def _tp_region_out_bwd(axis_name, _, g):
    return (g,)


_tp_region_out.defvjp(_tp_region_out_fwd, _tp_region_out_bwd)


# Sequence-parallel-activation variants (cfg.tp_seq_shard): the residual
# stream is SEQ-SHARDED [B, T/tp, D]; a tp region is entered by
# all-gathering the rows and left by reduce-scattering the partial
# outputs.  The pair is exactly conjugate (all_gather^T = reduce-scatter
# and vice versa), so gradients equal the unsharded model's the same way
# the f/g identity/psum pair's do (tests/test_tp_seq_shard.py).
@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_region_in(x, axis_name):
    return jax.lax.all_gather(x, axis_name, axis=1, tiled=True)


def _sp_region_in_fwd(x, axis_name):
    return _sp_region_in(x, axis_name), None


def _sp_region_in_bwd(axis_name, _, g):
    return (jax.lax.psum_scatter(g, axis_name, scatter_dimension=1,
                                 tiled=True),)


_sp_region_in.defvjp(_sp_region_in_fwd, _sp_region_in_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _sp_region_out(y, axis_name):
    return jax.lax.psum_scatter(y, axis_name, scatter_dimension=1,
                                tiled=True)


def _sp_region_out_fwd(y, axis_name):
    return _sp_region_out(y, axis_name), None


def _sp_region_out_bwd(axis_name, _, g):
    return (jax.lax.all_gather(g, axis_name, axis=1, tiled=True),)


_sp_region_out.defvjp(_sp_region_out_fwd, _sp_region_out_bwd)


def _enter_tp_region(x, cfg: LlamaConfig):
    """Bring the (possibly seq-sharded) residual stream into a tp
    parallel region: full rows out, conjugate backward."""
    if cfg.tp_seq_shard:
        return _sp_region_in(x, cfg.tp_axis)
    return _tp_region_in(x, cfg.tp_axis)


def _leave_tp_region(y, cfg: LlamaConfig):
    """Merge the shards' partial outputs back onto the residual stream
    layout (full psum, or summed seq shards under tp_seq_shard)."""
    if cfg.tp_seq_shard:
        return _sp_region_out(y, cfg.tp_axis)
    return _tp_region_out(y, cfg.tp_axis)




def _amax_quantize(x, eps: float = 1e-8):
    """Dynamic symmetric int8 quantization along the LAST axis: returns
    ``(q_int8, scale_f32)`` with ``scale = max(amax(|x|), eps) / 127``
    and ``q = round(x / scale)``.  ``|q| <= 127`` by construction (the
    amax element maps to exactly ±127), so no clip is needed — unlike
    the offline kernel quantizer (quant.py), whose per-output-channel
    scale divides elements from OTHER rows.  One definition for all four
    runtime uses (activations, K/V writes, queries, probabilities)."""
    x32 = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(x32), axis=-1, keepdims=True),
                        eps) / 127.0
    return jnp.round(x32 / scale).astype(jnp.int8), scale


class QuantDense(nn.Module):
    """Int8 linear layer for HBM-bound decode.

    Params: ``kernel`` int8 ``[in, out]`` + ``scale`` f32 ``[out]``
    (produced by :func:`bluefog_tpu.models.quant.quantize_llama_params`
    from a trained ``nn.Dense`` kernel).  The per-output-channel scale is
    constant along the contraction, so it commutes out of the matmul:
    ``x @ (W_q * s) == (x @ W_q) * s`` exactly.

    Two execution modes, measured on v5e (docs/performance.md round 4):

    * ``act_quant=False`` (weight-only, ``param_quant='int8'``): the dot
      runs in the compute dtype, so every weight element passes through
      an int8->bf16 convert on its way into the MXU — HBM streams 1 B/el
      but the convert path feeds matmuls at only ~280 GB/s effective.
    * ``act_quant=True`` (W8A8, ``param_quant='w8a8'``): activations
      quantize dynamically per token (one f32 amax scale per row — VPU
      work linear in the TINY activation, not the weights) and the dot
      runs natively s8 x s8 -> s32 on the MXU, which consumes int8
      weights at ~590-690 GB/s — ~2x the weight-only mode's wall-clock.
      Exact integer accumulation; the only extra rounding vs weight-only
      is the activations' int8 snap.

    ``out_f32`` returns f32 activations (the logits head).
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16
    out_f32: bool = False
    act_quant: bool = False

    @nn.compact
    def __call__(self, x):
        kernel = self.param("kernel", nn.initializers.zeros,
                            (x.shape[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        if self.act_quant:
            xq, xs = _amax_quantize(x)
            y = lax.dot_general(
                xq, kernel, (((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = y.astype(jnp.float32) * xs * scale
            return out if self.out_f32 else out.astype(self.dtype)
        y = jnp.dot(x.astype(self.dtype), kernel.astype(self.dtype))
        if self.out_f32:
            return y.astype(jnp.float32) * scale
        # scale in f32 then cast the product: keeps the module's
        # 'x @ (W_q * s) == (x @ W_q) * s exactly' contract — casting
        # the scale itself to bf16 first would add ~0.4% scale-rounding
        # error on top of the int8 snap.
        return (y.astype(jnp.float32) * scale).astype(self.dtype)


def _dense(cfg: LlamaConfig, feats: int, name: str):
    """The projection layer the config asks for: trained-precision
    ``nn.Dense`` or the int8 ``QuantDense`` (``param_quant='int8'``
    weight-only / ``'w8a8'`` native-int8-matmul)."""
    if cfg.param_quant != "none":
        return QuantDense(feats, dtype=cfg.dtype,
                          act_quant=cfg.param_quant == "w8a8", name=name)
    return nn.Dense(feats, use_bias=False, dtype=cfg.dtype,
                    param_dtype=jnp.float32, name=name)


class VocabParallelEmbed(nn.Module):
    """Token embedding with VOCAB rows sharded over ``tp_axis``.

    Each shard holds ``vocab/tp`` rows; out-of-range token ids look up a
    clamped row and are masked to zero, and the shards' partial results
    merge through ONE psum (the Megatron ``g`` operator, so the
    backward is identity and each shard's table gradient is exactly its
    own rows' — gradient parity in tests/test_vocab_parallel.py).
    Param path matches ``nn.Embed`` (``embedding``), so checkpoints move
    freely between layouts (the global array keeps the full
    ``[vocab, dim]`` shape; sharding happens in ``llama_param_specs``).
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        v_local = cfg.vocab_size // cfg.tp_size
        table = self.param(
            "embedding", nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0),
            (v_local, cfg.dim), jnp.float32)
        lo = lax.axis_index(cfg.tp_axis) * v_local
        local = tokens - lo
        valid = (local >= 0) & (local < v_local)
        x = jnp.take(table.astype(cfg.dtype),
                     jnp.clip(local, 0, v_local - 1), axis=0)
        x = jnp.where(valid[..., None], x, 0)
        # under tp_seq_shard this reduce-scatters straight to the
        # seq-sharded stream layout [B, T/tp, D] (half the wire bytes
        # of a full psum followed by a slice; the backward all-gathers
        # the disjoint row cotangents, so the table gradient still
        # covers every row)
        return _leave_tp_region(x, cfg)


@functools.partial(jax.custom_jvp, nondiff_argnums=(1,))
def _pmax_nograd(x, axis_name):
    """``lax.pmax`` with a zero tangent (pmax has no differentiation
    rule in JAX; as the logsumexp shift its gradient is exactly zero
    anyway — the shift cancels in ``logz - tlogit``)."""
    return lax.pmax(x, axis_name)


@_pmax_nograd.defjvp
def _pmax_nograd_jvp(axis_name, primals, tangents):
    (x,) = primals
    out = lax.pmax(x, axis_name)
    return out, jnp.zeros_like(out)


def vocab_parallel_xent(local_logits, targets, axis_name: str):
    """Exact next-token cross-entropy over VOCAB-SHARDED logits.

    ``local_logits``: ``[..., vocab/tp]`` (this shard's columns, in
    shard-index order — what a ``vocab_parallel`` Llama returns);
    ``targets``: ``[...]`` GLOBAL token ids.  Communicates one ``pmax``
    (stop-gradded — the standard logsumexp shift, exact either way) and
    two psums via the Megatron ``g`` operator so the backward stays
    per-shard (each shard's logit cotangent is the usual
    ``softmax - onehot`` restricted to its columns).  Every shard
    returns the IDENTICAL scalar mean loss, matching this framework's
    replicated-loss SPMD convention (optim/functional.py).
    """
    v_local = local_logits.shape[-1]
    logits32 = local_logits.astype(jnp.float32)
    m = _pmax_nograd(jnp.max(logits32, -1), axis_name)
    se = _tp_region_out(jnp.sum(jnp.exp(logits32 - m[..., None]), -1),
                        axis_name)
    logz = m + jnp.log(se)
    lo = lax.axis_index(axis_name) * v_local
    local = targets - lo
    valid = (local >= 0) & (local < v_local)
    tlogit = jnp.take_along_axis(
        logits32, jnp.clip(local, 0, v_local - 1)[..., None], -1)[..., 0]
    tlogit = _tp_region_out(jnp.where(valid, tlogit, 0.0), axis_name)
    return jnp.mean(logz - tlogit)


class Attention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        cfg = self.cfg
        hd = cfg.head_dim
        dense = lambda feats, name: _dense(cfg, feats, name)
        # under TP this module runs per-shard: local head counts; wo's
        # partial output merges below (Megatron column->row pattern,
        # entered through the 'f' operator — or the all-gather variant
        # under tp_seq_shard — so the backward is exact)
        tp = cfg.tp_axis is not None and cfg.tp_size > 1
        if tp:
            x = _enter_tp_region(x, cfg)
        b, t, _ = x.shape  # full rows (post-gather under tp_seq_shard)
        n_q = cfg.n_heads // cfg.tp_size
        n_kv = cfg.n_kv_heads // cfg.tp_size
        q = dense(n_q * hd, "wq")(x).reshape(b, t, n_q, hd)
        k = dense(n_kv * hd, "wk")(x).reshape(b, t, n_kv, hd)
        v = dense(n_kv * hd, "wv")(x).reshape(b, t, n_kv, hd)
        if cfg.decode:
            # rotary happens inside, at the cache-index positions
            out = self._decode_attend(q, k, v)
        else:
            positions = pos_offset + jnp.arange(t)
            q = rotary_embed(q, positions, cfg.rope_theta,
                             cfg.rope_scaling)
            k = rotary_embed(k, positions, cfg.rope_theta,
                             cfg.rope_scaling)
            if cfg.attn_mode == "ring":
                assert cfg.sp_axis is not None, "ring attention needs sp_axis"
                out = ring_attention(q, k, v, cfg.sp_axis, causal=True,
                                     impl=cfg.attn_impl)
            elif cfg.attn_mode == "ulysses":
                from bluefog_tpu.parallel.ulysses import ulysses_attention

                assert cfg.sp_axis is not None, \
                    "ulysses attention needs sp_axis"
                out = ulysses_attention(q, k, v, cfg.sp_axis, causal=True,
                                        impl=cfg.attn_impl,
                                        block_size=cfg.attn_block_size)
            elif cfg.attn_impl == "flash":
                from bluefog_tpu.parallel.pallas_attention import (
                    flash_attention)

                out = flash_attention(
                    q, k, v, causal=True,
                    block_q=min(cfg.attn_flash_block_size, t),
                    block_k=min(cfg.attn_flash_block_k, t))
            elif cfg.attn_impl == "splash":
                from bluefog_tpu.parallel.splash import splash_attention

                out = splash_attention(
                    q, k, v, causal=True,
                    block_q=min(cfg.attn_flash_block_size, t),
                    block_kv=min(cfg.attn_flash_block_k, t))
            elif cfg.attn_mode == "blockwise":
                out = blockwise_attention(q, k, v, cfg.attn_block_size,
                                          causal=True)
            else:
                out = full_attention(q, k, v, causal=True)
        out = out.reshape(b, t, n_q * hd)
        proj = dense(cfg.dim, "wo")(out)
        if tp:
            proj = _leave_tp_region(proj, cfg)
        return proj

    def _decode_attend(self, q, k, v):
        """Incremental attention against the layer's K/V cache.

        Appends this call's K/V at the cache index (rotary applied at the
        true absolute positions), then attends the queries over the whole
        cache with the causal mask in global coordinates
        (``_block_scores`` with ``q_offset=index``).  Works for both the
        multi-token prefill call and the one-token decode steps.
        """
        cfg = self.cfg
        b, t, n_kv, hd = k.shape
        max_len = cfg.max_seq_len
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((), jnp.int32))
        idx = ci.value
        positions = idx + jnp.arange(t)
        q = rotary_embed(q, positions, cfg.rope_theta, cfg.rope_scaling)
        k = rotary_embed(k, positions, cfg.rope_theta, cfg.rope_scaling)
        zero = jnp.zeros((), idx.dtype)
        # caches live KV-HEAD-MAJOR [B, KV, S, D] — the batch-dim layout
        # the attention dot_generals want, so no step pays a transpose
        # of the whole cache (measured: the [B, S, KV, D] layout cost
        # two cache-sized transposes per layer per decode step)
        k = jnp.swapaxes(k, 1, 2)  # [B, KV, T, D] (tiny: T=1 in decode)
        v = jnp.swapaxes(v, 1, 2)
        if cfg.kv_quant == "int8":
            # int8 cache, one f32 scale per (batch, kv_head, position)
            # vector.  Both scales commute out of the contractions (the
            # key scale is constant over head_dim, the value scale folds
            # into the probabilities), so the dequant below fuses into
            # the attention matmul reads — HBM streams int8.
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (b, n_kv, max_len, hd), jnp.int8)
            cks = self.variable("cache", "cached_key_scale", jnp.zeros,
                                (b, n_kv, max_len), jnp.float32)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (b, n_kv, max_len, hd), jnp.int8)
            cvs = self.variable("cache", "cached_value_scale", jnp.zeros,
                                (b, n_kv, max_len), jnp.float32)

            kq, ks = _amax_quantize(k)
            vq, vs = _amax_quantize(v)
            ks, vs = ks[..., 0], vs[..., 0]  # scale per (b, kv_head, t)
            kq_all = lax.dynamic_update_slice(ck.value, kq,
                                              (zero, zero, idx, zero))
            ks_all = lax.dynamic_update_slice(cks.value, ks,
                                              (zero, zero, idx))
            vq_all = lax.dynamic_update_slice(cv.value, vq,
                                              (zero, zero, idx, zero))
            vs_all = lax.dynamic_update_slice(cvs.value, vs,
                                              (zero, zero, idx))
            ck.value, cks.value = kq_all, ks_all
            cv.value, cvs.value = vq_all, vs_all
            ci.value = idx + t
            if cfg.decode_attn == "pallas" and t == 1:
                # fused single-launch decode step: in-kernel dequant,
                # probabilities kept float (parallel/pallas_decode.py)
                from bluefog_tpu.parallel.pallas_decode import (
                    decode_attention_int8)
                return decode_attention_int8(q, kq_all, ks_all, vq_all,
                                             vs_all, idx)
            if cfg.param_quant == "w8a8" and max_len <= 1024:
                # fully-integer attention: both contractions run s8xs8
                # on the MXU against the raw int8 cache — the cache
                # streams at native-dot rates (~600 GB/s measured)
                # instead of the ~280 GB/s convert-into-dot path.
                # LONG CONTEXT (static gate on the cache length) takes
                # the dequant path below instead: the integer path's
                # per-step probability re-quantization is VPU work
                # linear in S x heads and LOSES past ~1k positions
                # (round 5 measured, benchmarks/decode_200m_v5e1_r05:
                # w8a8 8.1k vs weight-only 10.1k tok/s at prompt 2048
                # before this gate; 10.9k after) — the round-4
                # "rule of thumb" is now the code's own dispatch.
                return _cached_attention_int8(q, kq_all, ks_all, vq_all,
                                              vs_all, idx)
            k_all = kq_all.astype(jnp.float32) * ks_all[..., None]
            v_all = vq_all.astype(jnp.float32) * vs_all[..., None]
        else:
            ck = self.variable("cache", "cached_key", jnp.zeros,
                               (b, n_kv, max_len, hd), cfg.dtype)
            cv = self.variable("cache", "cached_value", jnp.zeros,
                               (b, n_kv, max_len, hd), cfg.dtype)
            k_all = lax.dynamic_update_slice(
                ck.value, k.astype(cfg.dtype), (zero, zero, idx, zero))
            v_all = lax.dynamic_update_slice(
                cv.value, v.astype(cfg.dtype), (zero, zero, idx, zero))
            ck.value, cv.value, ci.value = k_all, v_all, idx + t
        if cfg.decode_attn == "pallas" and t == 1:
            from bluefog_tpu.parallel.pallas_decode import decode_attention
            return decode_attention(q, k_all, v_all, idx)
        # queries live at global positions [idx, idx+t); the causal mask
        # there also excludes the cache's unwritten (zero) tail
        return _cached_attention(q, k_all, v_all, idx)


def _cached_attention(q, k_all, v_all, idx):
    """Grouped-query attention over the whole K/V cache WITHOUT
    materializing repeated K/V heads.

    ``full_attention`` tiles K/V up to the query head count
    (``_repeat_kv``) — fine for training where the score matmul
    dominates, but decode is HBM-bound and the tiled cache multiplies
    its per-step attention traffic by ``n_heads / n_kv_heads`` (4x for
    Llama GQA).  Here the query heads reshape into ``[n_kv, group]``
    and both contractions run against the cache at its NATIVE kv-head
    count; any dequantization expression feeding ``k_all``/``v_all``
    (the int8 cache path) fuses into the dot operand reads.

    q: [B, T, n_q, D] (global positions ``idx + arange(T)``),
    k_all/v_all: KV-HEAD-MAJOR [B, n_kv, S, D] (the cache layout — the
    dots' batch dims lead, so no per-step transpose of the cache).
    Returns [B, T, n_q, D] in q's dtype.
    """
    b, t, n_q, d = q.shape
    n_kv, s = k_all.shape[1], k_all.shape[2]
    rep = n_q // n_kv
    q5 = q.reshape(b, t, n_kv, rep, d).astype(jnp.float32)
    scores = jnp.einsum("btkrd,bksd->bkrts", q5,
                        k_all.astype(jnp.float32)) * (1.0 / d ** 0.5)
    q_pos = idx + jnp.arange(t)
    mask = jnp.arange(s)[None, :] <= q_pos[:, None]  # [T, S]
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    # every query row sees at least its own key (just written), so no
    # fully-masked-row guard is needed
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkrts,bksd->btkrd", p, v_all.astype(jnp.float32))
    return out.reshape(b, t, n_q, d).astype(q.dtype)


def _cached_attention_int8(q, kq_all, ks_all, vq_all, vs_all, idx):
    """Grouped-query cached attention with BOTH contractions as native
    s8 x s8 -> s32 MXU dots (the ``param_quant='w8a8'`` +
    ``kv_quant='int8'`` decode path).

    The per-vector cache scales commute exactly: the key scale is
    constant along the head_dim contraction so it multiplies the score
    columns afterwards; the value scale varies along the position
    contraction so it folds INTO the probabilities before they are
    dynamically quantized (one amax scale per row — the same trick
    QuantDense plays on activations).  Rounding beyond the cache's own
    int8 snap: the queries' and probabilities' per-row int8 quant.

    q: [B, T, n_q, D] (positions ``idx + arange(T)``), kq_all/vq_all:
    int8 KV-HEAD-MAJOR [B, n_kv, S, D], ks_all/vs_all: f32
    [B, n_kv, S] (the cache layout — batch dims lead the dots, no
    per-step cache transpose).
    """
    b, t, n_q, d = q.shape
    n_kv, s = kq_all.shape[1], kq_all.shape[2]
    # the value contraction accumulates s8 x s8 into int32 with
    # worst-case magnitude 127*127*S, which crosses INT32_MAX near
    # S ~ 133k — refuse silently-overflowing cache lengths (chunk the
    # position contraction if longer contexts are ever needed)
    if s > 131072:
        raise ValueError(
            f"kv_quant='int8' + w8a8 decode supports cache length <= "
            f"131072 (int32 accumulator overflow at ~133k); got {s}")
    rep = n_q // n_kv
    qq, qs = _amax_quantize(q.reshape(b, t, n_kv, rep, d))
    s32 = jnp.einsum("btkrd,bksd->bkrts", qq, kq_all,
                     preferred_element_type=jnp.int32)
    # scales: q per row [B,T,KV,R,1] -> [B,KV,R,T,1]; k per position
    # [B,KV,S] broadcasts directly
    scores = (s32.astype(jnp.float32)
              * jnp.transpose(qs, (0, 2, 3, 1, 4))
              * ks_all[:, :, None, None, :]
              * (1.0 / d ** 0.5))
    q_pos = idx + jnp.arange(t)
    mask = jnp.arange(s)[None, :] <= q_pos[:, None]  # [T, S]
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)  # [B,KV,R,T,S]
    pv = p * vs_all[:, :, None, None, :]
    # eps far below any realistic row amax: a probability row sums to 1,
    # so amax >= 1/S — the tiny eps only guards fully-padded rows
    pq, ps = _amax_quantize(pv, eps=1e-30)
    o32 = jnp.einsum("bkrts,bksd->btkrd", pq, vq_all,
                     preferred_element_type=jnp.int32)
    out = o32.astype(jnp.float32) * jnp.transpose(ps, (0, 3, 1, 2, 4))
    return out.reshape(b, t, n_q, d).astype(q.dtype)


class FeedForward(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: _dense(cfg, feats, name)
        tp = cfg.tp_axis is not None and cfg.tp_size > 1
        if tp:
            x = _enter_tp_region(x, cfg)
        local_ffn = cfg.ffn_dim // cfg.tp_size
        gate = dense(local_ffn, "w1")(x)
        up = dense(local_ffn, "w3")(x)
        down = dense(cfg.dim, "w2")(nn.silu(gate) * up)
        if tp:
            down = _leave_tp_region(down, cfg)
        return down


def moe_combine_weights(probs: jax.Array, top_k: int, cap: int,
                        router: str = "topk") -> jax.Array:
    """Routing combine weights ``[g, G, E, cap]`` from per-group expert
    probabilities ``probs [g, G, E]`` — a pure function so the routing
    contract is unit-testable in isolation (tests/test_moe.py asserts
    the occupancy/drop accounting directly on it).

    ``router="topk"``: token-choice — each token takes its ``top_k``
    experts, bounded by the per-expert per-group capacity ``cap``
    (overflow tokens are dropped to the residual).  Autoregressive-safe.

    ``router="expert_choice"`` (Zhou et al. 2022): each expert takes its
    top-``cap`` tokens per group — dropless and perfectly load-balanced
    BY CONSTRUCTION (no aux loss needed), but NOT causal (which earlier
    tokens an expert keeps depends on later tokens in the group); for
    encoder/bidirectional stacks.  ``cap`` is clamped to the group size.
    """
    g, G, E = probs.shape
    if router == "expert_choice":
        cap = min(cap, G)  # an expert cannot take more than G tokens
        scores = jnp.swapaxes(probs, 1, 2)          # [g, E, G]
        gate_vals, idx = lax.top_k(scores, cap)     # [g, E, cap]
        onehot = jax.nn.one_hot(idx, G, dtype=jnp.float32)
        # combine[g, s, e, c] = gate of token s in expert e's slot c
        return jnp.einsum("gecs,gec->gsec", onehot, gate_vals)
    # top-k selection: k rounds of argmax with masking (k is tiny)
    masked = probs
    combine = jnp.zeros((g, G, E, cap), jnp.float32)
    counts = jnp.zeros((g, E), jnp.int32)
    for _ in range(top_k):
        idx = jnp.argmax(masked, axis=-1)               # [g, G]
        # gate from MASKED probs: if the softmax tail underflowed to
        # exact zero, a later round's argmax re-picks an earlier expert —
        # reading the unmasked prob would double-count it with full
        # weight; the masked value is 0 for re-picks.
        gate = jnp.take_along_axis(masked, idx[..., None],
                                   axis=-1)[..., 0]     # [g, G]
        onehot = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        # position of each token within its expert's per-group queue,
        # offset by what previous rounds already enqueued
        pos = jnp.cumsum(onehot, axis=1) - onehot + counts[:, None, :]
        pos_tok = jnp.sum(pos * onehot, axis=-1)        # [g, G]
        keep = pos_tok < cap
        combine = combine + (
            gate[..., None, None]
            * jax.nn.one_hot(idx, E)[..., None]
            * jax.nn.one_hot(pos_tok, cap)[..., None, :]
            * keep[..., None, None])
        counts = counts + jnp.sum(
            onehot * keep[..., None].astype(jnp.int32), axis=1)
        masked = masked * (1.0 - onehot.astype(masked.dtype))
    return combine


class MoEFeedForward(nn.Module):
    """Top-k routed mixture-of-experts SwiGLU FFN with expert parallelism.

    TPU-first design: routing is computed identically on every ep shard
    (tokens are replicated over ``ep_axis``), dispatch/combine are static
    einsums against a capacity-bounded one-hot tensor (no dynamic shapes,
    no host round trips), each shard evaluates only its LOCAL experts as
    one batched ``[local_E, slots, d]`` einsum on the MXU, and the
    shards' partial outputs merge with ONE psum (through the Megatron-
    style g operator; the token stream enters through f so gradients are
    exact — see ``_tp_region_in/_tp_region_out``).  Tokens over an
    expert's capacity are dropped (they ride the residual), the standard
    static-shape MoE contract.

    Routing is GROUPED (``cfg.moe_group_size``): tokens route within
    fixed-size groups with per-group capacity, so the one-hot
    dispatch/combine tensors are ``[g, G, E, cap]`` with
    ``g*G*E*cap = capacity_factor*top_k*s*G`` elements — LINEAR in the
    token count ``s`` for fixed ``G`` (an ungrouped capacity grows with
    ``s`` and the tensors are O(s^2), which OOMs at real sequence
    lengths).
    """

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        b, t, d = x.shape
        E = cfg.n_experts
        local_E = E // cfg.ep_size
        ep = cfg.ep_axis is not None and cfg.ep_size > 1
        s = b * t
        # effective group: the largest divisor of s <= moe_group_size
        # (static Python arithmetic — shapes stay compile-time constants)
        G = s
        if 0 < cfg.moe_group_size < s:
            G = cfg.moe_group_size
            while s % G:
                G -= 1
        g = s // G
        # Two independent paths enter the expert region, each wrapped in
        # its OWN f operator (identity fwd / psum bwd) so every backward
        # contribution is summed over ep exactly once: the token stream
        # (expert inputs) and the router logits.  The router itself runs
        # on the raw x OUTSIDE the region — it is a replicated param, and
        # wrapping its output (not its input) is what makes its gradient
        # the full cross-expert sum instead of a per-shard partial.
        flat_raw = x.reshape(s, d)
        if ep:
            x = _tp_region_in(x, cfg.ep_axis)
        flat = x.reshape(g, G, d)
        cap = max(1, int(cfg.capacity_factor * G * cfg.moe_top_k / E))

        logits_raw = nn.Dense(E, use_bias=False, dtype=jnp.float32,
                              param_dtype=jnp.float32, name="router")(
                                  flat_raw.astype(jnp.float32))
        logits = _tp_region_in(logits_raw, cfg.ep_axis) if ep else logits_raw
        probs = jax.nn.softmax(logits, axis=-1).reshape(g, G, E)

        combine = moe_combine_weights(probs, cfg.moe_top_k, cap,
                                      cfg.moe_router)
        cap = combine.shape[-1]  # expert_choice clamps cap to G
        if 0 < cfg.moe_group_size < s and G < cfg.moe_group_size // 2:
            # awkward token counts (odd/prime b*t) can collapse the
            # divisor far below the requested group — per-group capacity
            # shrinks with it and routing quality degrades silently;
            # surface it (pad b*t to a rounder count to fix)
            from bluefog_tpu.logging_util import get_logger
            get_logger().warning(
                "MoE grouped routing: token count %d has no divisor "
                "near moe_group_size=%d; effective group collapsed to "
                "%d (capacity %d tokens/expert/group). Pad the "
                "batch*seq token count to a multiple of the group size "
                "to restore routing quality.", s, cfg.moe_group_size,
                G, cap)
        dispatch = (combine > 0.0).astype(cfg.dtype)  # [g, G, E, cap]
        # my shard's expert slice
        if ep:
            e_lo = jax.lax.axis_index(cfg.ep_axis) * local_E
        else:
            e_lo = 0
        disp_local = lax.dynamic_slice_in_dim(dispatch, e_lo, local_E, 2)
        comb_local = lax.dynamic_slice_in_dim(
            combine.astype(cfg.dtype), e_lo, local_E, 2)

        # gather each expert's slots across all groups into one MXU batch
        expert_in = jnp.einsum("gsec,gsd->egcd", disp_local,
                               flat.astype(cfg.dtype))
        expert_in = expert_in.reshape(local_E, g * cap, d)
        h = cfg.ffn_dim
        w1 = self.param("w1", nn.initializers.lecun_normal(
            in_axis=-2, out_axis=-1), (local_E, d, h), jnp.float32)
        w3 = self.param("w3", nn.initializers.lecun_normal(
            in_axis=-2, out_axis=-1), (local_E, d, h), jnp.float32)
        w2 = self.param("w2", nn.initializers.lecun_normal(
            in_axis=-2, out_axis=-1), (local_E, h, d), jnp.float32)
        gate_h = jnp.einsum("ecd,edh->ech", expert_in, w1.astype(cfg.dtype))
        up_h = jnp.einsum("ecd,edh->ech", expert_in, w3.astype(cfg.dtype))
        expert_out = jnp.einsum("ech,ehd->ecd", nn.silu(gate_h) * up_h,
                                w2.astype(cfg.dtype))
        expert_out = expert_out.reshape(local_E, g, cap, d)
        out = jnp.einsum("egcd,gsec->gsd", expert_out, comb_local)
        if ep:
            out = _tp_region_out(out, cfg.ep_axis)
        # load-balancing auxiliary loss (Switch Transformer eq. 4) —
        # always sown (the scanned stack declares an intermediates axis);
        # trainers add cfg.moe_aux_weight * total to the objective (the
        # shipped loss builders do — see llama_pp_loss_fn and
        # examples/llama_benchmark.py).  Computed from the UNWRAPPED
        # logits: the aux term is a replicated computation outside the
        # expert region, so adding it to the loss gives the unsharded
        # router gradient exactly (through the f-wrapped logits its
        # backward psum would scale the aux contribution by ep_size).
        probs_all = jax.nn.softmax(logits_raw, axis=-1)
        frac_tokens = jnp.mean(
            jax.nn.one_hot(jnp.argmax(probs_all, -1), E,
                           dtype=jnp.float32), axis=0)
        frac_probs = jnp.mean(probs_all, axis=0)
        self.sow("intermediates", "moe_aux_loss",
                 E * jnp.sum(frac_tokens * frac_probs))
        return out.reshape(b, t, d).astype(x.dtype)


class Block(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        cfg = self.cfg
        naxis = cfg.tp_axis if cfg.tp_seq_shard else None
        x = x + Attention(cfg, name="attention")(
            RMSNorm(cfg.norm_eps, grad_psum_axis=naxis,
                    name="attention_norm")(x), pos_offset)
        ffn_cls = MoEFeedForward if cfg.n_experts else FeedForward
        name = "moe_ffn" if cfg.n_experts else "feed_forward"
        x = x + ffn_cls(cfg, name=name)(
            RMSNorm(cfg.norm_eps, grad_psum_axis=naxis,
                    name="ffn_norm")(x))
        return x


class _ScanBlock(nn.Module):
    """nn.scan adapter: Block with a (carry, out) return signature."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, pos_offset):
        return Block(self.cfg, name="block")(x, pos_offset), None


class Llama(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, tokens, pos_offset=0, return_hidden=False,
                 all_logits=False):
        """tokens: [B, T_local] int32 -> logits [B, T_local, vocab] f32
        (with ``cfg.vocab_parallel``: [B, T_local, vocab/tp] — this
        shard's columns; train against ``vocab_parallel_xent``).

        ``return_hidden=True`` stops after the final RMSNorm and returns
        the [B, T_local, dim] hidden states instead of logits — the
        entry point for the chunked head+cross-entropy path
        (``llama_chunked_xent_loss_fn``), which never materializes the
        full [B, T, vocab] logits.  Init with the default so the head
        params exist; apply-with-return_hidden simply leaves them
        unused.

        ``all_logits=True`` keeps every position's logits in decode
        layout (normally only the final position survives — generation
        samples nothing else).  Speculative decoding's verify step needs
        it: ONE multi-token cached forward scores a whole draft window,
        so acceptance reads the target distribution at each drafted
        position.  No-op outside decode layout."""
        cfg = self.cfg
        assert tokens.shape[1] <= cfg.max_seq_len, (
            f"sequence shard {tokens.shape[1]} exceeds max_seq_len "
            f"{cfg.max_seq_len}")
        if cfg.tp_seq_shard:
            assert tokens.shape[1] % cfg.tp_size == 0, (
                f"sequence length {tokens.shape[1]} must divide by "
                f"tp_size ({cfg.tp_size}) under tp_seq_shard")
        if cfg.vocab_parallel:
            # with tp_seq_shard the embed reduce-scatters straight to
            # this shard's rows [B, T/tp, D] — the layout the whole
            # residual stream lives in between tp regions
            x = VocabParallelEmbed(cfg, name="tok_embeddings")(tokens)
        else:
            x = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                         param_dtype=jnp.float32,
                         name="tok_embeddings")(tokens)
        policy = _remat_policies()[cfg.remat_policy]
        if cfg.scan_layers:
            # one compiled block, scanned n_layers times; params get a
            # leading [n_layers] axis under "layers" — trace/compile cost
            # stops growing with depth
            body = _ScanBlock
            if cfg.remat:
                # prevent_cse=False: XLA's loop lowering already blocks the
                # problematic CSE under scan; the default True would insert
                # an opt-barrier per scanned layer
                body = nn.checkpoint(body, static_argnums=(), policy=policy,
                                     prevent_cse=False)
            scan_cls = nn.scan(
                body,
                variable_axes={"params": 0, "intermediates": 0, "cache": 0},
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.n_layers,
                metadata_params={nn.meta.PARTITION_NAME: None},
            )
            x, _ = scan_cls(cfg, name="layers")(x, pos_offset)
        else:
            block_cls = Block
            if cfg.remat:
                block_cls = nn.checkpoint(Block, static_argnums=(),
                                          policy=policy)
            for i in range(cfg.n_layers):
                x = block_cls(cfg, name=f"layer_{i}")(x, pos_offset)
        x = RMSNorm(cfg.norm_eps,
                    grad_psum_axis=cfg.tp_axis if cfg.tp_seq_shard
                    else None, name="norm")(x)
        if cfg.decode and not all_logits:
            # generation only ever samples from the final position — skip
            # the other T-1 head matmuls and the [B, T, vocab] logits
            # buffer (at 8k prompt x 128k vocab that is ~4 GB of f32)
            x = x[:, -1:]
        if return_hidden:
            return x
        if cfg.param_quant != "none":
            # int8 head: HBM streams the int8 kernel, the per-channel
            # scale lands in f32 — the logits keep f32 dynamic range
            # around int8-rounded products
            logits = QuantDense(cfg.vocab_size, dtype=cfg.dtype,
                                out_f32=True,
                                act_quant=cfg.param_quant == "w8a8",
                                name="output")(x)
        elif cfg.vocab_parallel:
            # column-parallel over VOCAB: each shard emits its own
            # logits columns [B, T, vocab/tp] — NOT psum-merged (the
            # full matrix would be the memory the layout exists to
            # avoid); train against vocab_parallel_xent.  x enters the
            # parallel region through f so the backward psum is exact
            # (under tp_seq_shard the entry re-gathers the rows ONCE,
            # since each shard's vocab columns are needed for EVERY
            # row's softmax).
            head_dtype = jnp.float32 if cfg.logits_dot_in_fp32 else cfg.dtype
            logits = nn.Dense(cfg.vocab_size // cfg.tp_size,
                              use_bias=False, dtype=head_dtype,
                              param_dtype=jnp.float32, name="output")(
                                  _enter_tp_region(x, cfg))
        else:
            head_dtype = jnp.float32 if cfg.logits_dot_in_fp32 else cfg.dtype
            logits = nn.Dense(cfg.vocab_size, use_bias=False,
                              dtype=head_dtype, param_dtype=jnp.float32,
                              name="output")(x)
        return logits.astype(jnp.float32)


def llama_circular_layout(variables, n_stages: int, n_loops: int,
                          inverse: bool = False):
    """Permute the scanned block's layer axis into (or, with
    ``inverse=True``, back out of) the circular-pipeline storage order —
    apply BEFORE ``rank_major`` when training with
    ``llama_pp_loss_fn(..., n_loops>1)``, and inversely when exporting a
    checkpoint to the natural layer order.  See
    ``parallel.pipeline.circular_layer_permutation``."""
    from bluefog_tpu.parallel.pipeline import circular_layer_permutation

    block = variables["params"]["layers"]["block"]
    n_layers = jax.tree.leaves(block)[0].shape[0]
    perm = circular_layer_permutation(n_layers, n_stages, n_loops)
    if inverse:
        perm = np.argsort(perm)
    permuted = jax.tree.map(lambda a: jnp.take(a, perm, axis=0), block)
    out = dict(variables)
    out["params"] = dict(variables["params"])
    out["params"]["layers"] = {"block": permuted}
    return out


def llama_pp_loss_fn(cfg: LlamaConfig, *, pp_axis: str, n_stages: int,
                     n_micro: int, n_loops: int = 1):
    """Build a next-token cross-entropy ``loss_fn(params, (inputs,
    targets))`` that runs the decoder stack as a GPipe pipeline over
    ``pp_axis`` (see ``bluefog_tpu.parallel.pipeline.gpipe``) — pipeline
    parallelism, a capability past the reference's DP-only scope
    (SURVEY.md §2.3: PP absent there).

    Requires ``cfg.scan_layers=True``: the scanned parameter layout gives
    every block leaf a leading ``[n_layers]`` axis, which
    ``llama_param_specs(pp_axis=...)`` shards over the pipeline axis so
    each stage holds ``n_layers / n_stages`` layers.  The param TREE is
    identical to the plain scanned model — checkpoints move freely
    between pipeline layouts.

    The returned loss is per-shard MASKED: only the last stage's value is
    the real loss (other stages return 0).  Feed it to
    ``build_train_step(pp_axis=...)``, which psums the loss over the
    pipeline axis and reduces gradients for pp-replicated leaves
    (embeddings / final norm / head).

    Composes with sequence parallelism (``cfg.attn_mode='ring'``): rotary
    offsets are derived from the sp shard index internally, and each sp
    shard's partial loss is averaged by the train step's ``sp_axis``
    reduction.  Batch size must divide by ``n_micro``.

    ``n_loops > 1`` switches to the circular (interleaved) schedule:
    each stage holds ``n_loops`` round-robin layer chunks and
    microbatches ride the ring ``n_loops`` times, shrinking the bubble
    to ``(S-1)/(n_loops*M + S-1)``.  Params must be permuted into the
    circular storage order first (``llama_circular_layout``) and
    ``n_micro >= n_stages`` is required.
    """
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True "
                         "(the stacked-layer param layout is what shards "
                         "over the pipeline axis)")
    if cfg.n_layers % (n_stages * n_loops):
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide by "
                         f"n_stages*n_loops ({n_stages}*{n_loops})")
    if cfg.tp_seq_shard:
        raise ValueError(
            "tp_seq_shard is not supported in the pipeline loss builder "
            "yet (the stage boundary would have to carry seq-sharded "
            "activations through the pp permute); use it with the plain "
            "stack, or pp without tp_seq_shard")

    from bluefog_tpu.parallel.pipeline import gpipe, gpipe_circular

    # the exact modules Llama.__call__ uses — applied to param subtrees,
    # so the pp path cannot diverge from the plain model's math
    block = Block(cfg)
    final_norm = RMSNorm(cfg.norm_eps)
    head_dtype = jnp.float32 if cfg.logits_dot_in_fp32 else cfg.dtype
    if cfg.vocab_parallel:
        embed = VocabParallelEmbed(cfg)
        head = nn.Dense(cfg.vocab_size // cfg.tp_size, use_bias=False,
                        dtype=head_dtype, param_dtype=jnp.float32)
    else:
        embed = nn.Embed(cfg.vocab_size, cfg.dim, dtype=cfg.dtype,
                         param_dtype=jnp.float32)
        head = nn.Dense(cfg.vocab_size, use_bias=False, dtype=head_dtype,
                        param_dtype=jnp.float32)
    want_aux = cfg.n_experts > 0 and cfg.moe_aux_weight > 0.0

    def loss_fn(params, batch):
        import optax

        inp, tgt = batch  # [B, T_local] int32
        p = params["params"]
        b, t = inp.shape
        if b % n_micro:
            raise ValueError(f"batch size {b} must divide by n_micro "
                             f"({n_micro})")
        x = embed.apply({"params": p["tok_embeddings"]}, inp)  # [B, T, D]
        pos_offset = 0
        if cfg.attn_mode in ("ring", "ulysses"):
            assert cfg.sp_axis is not None, "sequence parallelism needs " \
                "sp_axis"
            pos_offset = lax.axis_index(cfg.sp_axis) * t
        bm = b // n_micro
        x_micro = x.reshape(n_micro, bm, t, cfg.dim)
        layer_p = p["layers"]["block"]  # per-shard: leaves [L/S, ...]

        def per_layer(x, lp):
            if want_aux:
                y, mut = block.apply({"params": lp}, x, pos_offset,
                                     mutable=["intermediates"])
                aux = sum(jnp.sum(v) for v in
                          jax.tree.leaves(mut["intermediates"]))
                return y, aux
            return block.apply({"params": lp}, x, pos_offset), jnp.float32(0)

        body = per_layer
        if cfg.remat:
            body = jax.checkpoint(per_layer,
                                  policy=_remat_policies()[cfg.remat_policy],
                                  prevent_cse=False)

        def stage_fn(lp, x):
            y, aux = lax.scan(body, x, lp)
            return y, jnp.sum(aux)

        if n_loops > 1:
            # circular layout: this shard's [L/S] layers are its n_loops
            # chunks in loop order (params permuted by
            # llama_circular_layout before sharding)
            chunks = jax.tree.map(
                lambda a: a.reshape((n_loops, a.shape[0] // n_loops)
                                    + a.shape[1:]), layer_p)
            outs, aux_sum = gpipe_circular(
                stage_fn, chunks, x_micro, pp_axis, n_stages, n_loops,
                with_aux=True)
        else:
            outs, aux_sum = gpipe(stage_fn, layer_p, x_micro, pp_axis,
                                  n_stages, with_aux=True)
        h = outs.reshape(b, t, cfg.dim)
        # final norm + head are pp-replicated params; every stage runs
        # them (SPMD lockstep — no extra wall-clock) but only the last
        # stage's loss survives the mask, so their gradients are nonzero
        # exactly once across the axis and the train step's pp psum
        # restores the replicated update.
        h = final_norm.apply({"params": p["norm"]}, h)
        if cfg.vocab_parallel:
            hl = _tp_region_in(h, cfg.tp_axis)
            logits = head.apply({"params": p["output"]},
                                hl).astype(jnp.float32)
            loss = vocab_parallel_xent(logits, tgt, cfg.tp_axis)
        else:
            logits = head.apply({"params": p["output"]},
                                h).astype(jnp.float32)
            loss = jnp.mean(
                optax.softmax_cross_entropy_with_integer_labels(logits, tgt))
        stage = lax.axis_index(pp_axis)
        loss = jnp.where(stage == n_stages - 1, loss, 0.0)
        if want_aux:
            # each stage owns its layers' routers, so its aux rides its
            # OWN loss term (unmasked — the train step's pp psum then
            # totals CE + every stage's aux).  aux_sum is over the M real
            # microbatch ticks; /M gives the per-microbatch mean — the
            # grouped-routing analogue of the unsharded full-batch aux
            # (identical to it when n_micro == 1).
            loss = loss + cfg.moe_aux_weight * aux_sum / n_micro
        return loss

    return loss_fn


def chunked_xent(h, w_kernel, targets, *, n_chunks: int = 8,
                 dot_in_fp32: bool = True):
    """Next-token cross-entropy computed CHUNK BY CHUNK over the sequence
    so the full ``[B, T, vocab]`` logits never materialize.

    ``h``: [B, T, dim] final-norm hidden states (``Llama.__call__`` with
    ``return_hidden=True``); ``w_kernel``: [dim, vocab] head kernel;
    ``targets``: [B, T] int32.  Each of the ``n_chunks`` sequence chunks
    computes its logits, log-sum-exp and target gather inside a
    ``jax.checkpoint`` region iterated by ``lax.map``: forward holds one
    [B, T/n_chunks, vocab] block at a time, backward recomputes it — at
    8B scale (seq 4096, vocab 128k) that is 16 GB of f32 logits (+ the
    same again for their cotangent) that never exist at once.  Exact:
    same f32 softmax math as the monolithic head (parity in
    tests/test_models.py)."""
    b, s, _ = h.shape
    if s % n_chunks:
        raise ValueError(f"seq len {s} % n_chunks {n_chunks} != 0")
    import optax

    c = s // n_chunks
    dtype = jnp.float32 if dot_in_fp32 else h.dtype
    hc = jnp.swapaxes(h.reshape(b, n_chunks, c, h.shape[-1]), 0, 1)
    tc = jnp.swapaxes(targets.reshape(b, n_chunks, c), 0, 1)

    @jax.checkpoint
    def one(args):
        hx, t = args
        logits = jnp.dot(hx.astype(dtype),
                         w_kernel.astype(dtype)).astype(jnp.float32)
        return jnp.sum(
            optax.softmax_cross_entropy_with_integer_labels(logits, t))

    return jnp.sum(lax.map(one, (hc, tc))) / (b * s)


def llama_chunked_xent_loss_fn(cfg: LlamaConfig, *, n_chunks: int = 8):
    """Build ``loss_fn(params, (inputs, targets))`` that runs the decoder
    stack normally but the head + cross-entropy through ``chunked_xent``
    (the fused/blockwise head path — the full logits tensor is the
    single largest activation of the train step at every size).  Not
    compatible with ``vocab_parallel`` (which has its own exact sharded
    xent) or MoE-aux configs (use the plain loss with intermediates)."""
    if cfg.vocab_parallel:
        raise ValueError("chunked xent: use vocab_parallel_xent with "
                         "vocab_parallel configs")
    if cfg.tp_seq_shard:
        raise ValueError("chunked xent: hidden states are seq-sharded "
                         "under tp_seq_shard but targets are not")
    if cfg.n_experts and cfg.moe_aux_weight > 0.0:
        raise ValueError("chunked xent does not collect MoE aux "
                         "intermediates; use the plain loss")
    model = Llama(cfg)

    def loss_fn(params, batch):
        inp, tgt = batch
        h = model.apply(params, inp, return_hidden=True)
        w = params["params"]["output"]["kernel"]
        return chunked_xent(h, w, tgt, n_chunks=n_chunks,
                            dot_in_fp32=cfg.logits_dot_in_fp32)

    return loss_fn


def llama_param_specs(params_or_shapes, rank_axis: Optional[str] = "bf",
                      tp_axis: Optional[str] = "tp",
                      ep_axis: Optional[str] = "ep",
                      pp_axis: Optional[str] = None,
                      vocab_axis: Optional[str] = None):
    """PartitionSpec tree for rank-major Llama params under model
    parallelism: column-parallel kernels (wq/wk/wv/w1/w3) shard their
    OUTPUT (last) dim over ``tp_axis``, row-parallel kernels (wo/w2)
    their INPUT (second-to-last) dim; MoE expert tensors (under
    ``moe_ffn``) shard their EXPERT dim over ``ep_axis``; with
    ``pp_axis`` (pipeline parallelism — requires the scanned-layer
    layout) every leaf under the scanned block additionally shards its
    leading ``[n_layers]`` axis over the pipeline axis, so each stage
    holds only its own layers.  The router and everything outside the
    decoder stack (embeddings, final norm, logits head) stay replicated
    — unless ``vocab_axis`` is given (``cfg.vocab_parallel`` models):
    then the embedding shards its VOCAB rows (dim 0) and the logits
    head its VOCAB columns (last dim) over that axis.
    Works for both unrolled and scanned layouts (the kernel rank decides
    where the sharded dim sits).  Feed the result to
    ``optim.functional.build_train_step(param_specs=...)``."""
    from jax.sharding import PartitionSpec as P

    column = ("wq", "wk", "wv", "w1", "w3")
    row = ("wo", "w2")

    def spec_for(path, leaf):
        names = "/".join(
            str(getattr(p, "key", getattr(p, "name", p))) for p in path)
        tagged = f"/{names}/"
        # leaf shapes come WITHOUT the leading rank axis (pass the tree
        # that model.init returned); the produced specs are for the
        # rank-major global arrays, so the rank axis is prepended here
        nd = len(leaf.shape)
        leaf_name = str(getattr(path[-1], "key",
                                getattr(path[-1], "name", path[-1])))
        # QuantDense per-output-channel scales ([.., out]) shard exactly
        # like their kernel's OUTPUT dim: over tp for column-parallel
        # layers, replicated for row-parallel ones (whose tp-sharded dim
        # is the input)
        is_scale = leaf_name == "scale"
        dims = [None] * nd
        # scanned decoder stack: leading dim is the layer axis
        if pp_axis is not None and "/layers/" in tagged and nd >= 1:
            dims[0] = pp_axis
        if vocab_axis is not None and "/tok_embeddings/" in tagged \
                and nd >= 2:
            dims[0] = vocab_axis  # [V, D]: shard the vocab rows
        elif vocab_axis is not None and "/output/" in tagged and nd >= 1:
            dims[-1] = vocab_axis  # kernel [D, V] / scale [V]: columns
        elif "/moe_ffn/" in tagged:
            if ep_axis is not None and "/router/" not in tagged and nd >= 3:
                dims[-3] = ep_axis  # [.., E, in, out]: shard E
        elif any(f"/{k}/" in tagged for k in column) \
                and (nd >= 2 or (is_scale and nd >= 1)):
            if tp_axis is not None:
                dims[-1] = tp_axis
        elif any(f"/{k}/" in tagged for k in row) and nd >= 2 \
                and not is_scale:
            if tp_axis is not None:
                dims[-2] = tp_axis
        while dims and dims[-1] is None:  # canonical: no trailing Nones
            dims.pop()
        if rank_axis is None:
            # non-rank-major trees (e.g. replicated decode params whose
            # only sharded axis is tp): specs without the rank dim
            return P(*dims)
        return P(rank_axis, *dims)

    return jax.tree_util.tree_map_with_path(spec_for, params_or_shapes)
