"""ResNet family, TPU-first.

Capability parity: the reference benchmarks torchvision ResNets (reference
examples/pytorch_benchmark.py --model resnet50, examples/pytorch_resnet.py:54).
This is a fresh flax.linen implementation designed for the MXU:

* NHWC layout (XLA's native conv layout on TPU),
* bf16 activations/conv compute with f32 parameters and f32 batch-norm
  statistics (the standard mixed-precision recipe),
* static shapes everywhere; stride/padding arithmetic resolved at trace time.

Under decentralized data-parallel training each rank keeps *local* batch-norm
statistics (the reference does the same — torch BN is per-process, no
SyncBatchNorm in its examples).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn
from jax import lax

ModuleDef = Any


class _PallasConv1x1(nn.Module):
    """1x1 conv whose backward is the Pallas fused dgrad+wgrad kernel
    (parallel/pallas_conv.py) — one pass over x and dy instead of XLA's
    two separate transposed convolutions.  Parameter layout stays
    ``kernel [1, 1, ci, co]`` so checkpoints interchange with nn.Conv."""

    features: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from bluefog_tpu.parallel.pallas_conv import conv1x1

        ci = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (1, 1, ci, self.features), jnp.float32)
        return conv1x1(x.astype(self.dtype),
                       kernel.reshape(ci, self.features).astype(self.dtype),
                       self.strides[0])


class _SpaceToDepthInit(nn.Module):
    """The stem 7x7/s2 conv, computed space-to-depth (MLPerf ResNet
    trick): 3 input channels use 3/128 of the MXU's reduction depth, so
    the 224^2x3 conv is re-indexed as an equivalent 4x4/s1 conv over the
    112^2x12 2x2-space-to-depth layout — identical numerics (pure weight
    re-indexing; the parameter stays [7, 7, 3, F] so checkpoints are
    interchangeable with the plain nn.Conv stem), ~4x better MXU
    utilization on the stem."""

    features: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        b, h, w, c = x.shape
        f = self.features
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (7, 7, c, f), jnp.float32)
        # x[2P+a, 2Q+b, c] -> X[P, Q, (a, b, c)]
        xs = x.reshape(b, h // 2, 2, w // 2, 2, c)
        xs = xs.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
        # w4[m, n, (a, b, c), o] = w7[2m + a - 1, 2n + b - 1, c, o]
        # (out-of-range rows are the zero padding)
        w8 = jnp.pad(kernel, ((1, 0), (1, 0), (0, 0), (0, 0)))
        w4 = w8.reshape(4, 2, 4, 2, c, f).transpose(0, 2, 1, 3, 4, 5)
        w4 = w4.reshape(4, 4, 4 * c, f)
        return lax.conv_general_dilated(
            xs.astype(self.dtype), w4.astype(self.dtype),
            window_strides=(1, 1), padding=[(2, 1), (2, 1)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef
    conv1x1: ModuleDef = None  # expansion/proj 1x1s (Pallas bwd) if set

    @nn.compact
    def __call__(self, x):
        expand = self.conv1x1 or (
            lambda f, s=(1, 1), name=None: self.conv(f, (1, 1), s,
                                                     name=name))
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = expand(self.filters * 4)(y)
        # zero-init the last BN scale so each block starts as identity
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = expand(self.filters * 4, self.strides,
                              name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: jnp.dtype = jnp.bfloat16
    # space-to-depth stem: numerics-identical, checkpoint-compatible,
    # measurably faster on the MXU (see _SpaceToDepthInit); disable only
    # for odd input sizes (needs H and W divisible by 2)
    space_to_depth: bool = True
    # Route the bottleneck expansion/projection 1x1 convs through the
    # Pallas fused-backward kernel (parallel/pallas_conv.py).  Numerics
    # match XLA (tests/test_pallas_conv.py); module auto-names differ
    # from the nn.Conv layout, so flip it only on fresh params.
    pallas_conv1x1: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32)
        x = x.astype(self.dtype)
        if self.space_to_depth and x.shape[1] % 2 == 0 and x.shape[2] % 2 == 0:
            x = _SpaceToDepthInit(self.num_filters, self.dtype,
                                  name="conv_init")(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_kwargs = {}
        if self.pallas_conv1x1 and self.block_cls is BottleneckBlock:
            block_kwargs["conv1x1"] = partial(_PallasConv1x1,
                                              dtype=self.dtype)
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2**i, strides=strides,
                                   conv=conv, norm=norm, **block_kwargs)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock)
