"""Chrome-tracing timeline.

Reference parity: the C++ ``Timeline``/``TimelineWriter`` pair
(bluefog/common/timeline.{h,cc}) which streams per-op activity spans to
``$BLUEFOG_TIMELINE<rank>.json`` via a dedicated writer thread.  Here the
heavyweight path (device execution) is already traced by ``jax.profiler``;
this module records the *framework-level* activity spans (enqueue, compute,
update phases) with the same file format so the reference's timeline
tooling (chrome://tracing) works unchanged.

Events are handed to a background writer thread over a queue, like the
reference's lock-free SPSC design (timeline.h:65-67) — the Python GIL makes
a queue.Queue equivalent.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
from contextlib import contextmanager
from typing import Optional

__all__ = ["Timeline", "get_timeline", "start_timeline", "stop_timeline"]


class Timeline:
    def __init__(self, path: str, rank: int = 0):
        self.path = f"{path}{rank}.json"
        self.rank = rank
        self._t0 = time.perf_counter()
        self._queue: "queue.Queue" = queue.Queue()
        self._file = open(self.path, "w")
        self._file.write("[\n")
        self._first = True
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._thread.start()
        self._open_spans = {}
        atexit.register(self.close)

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _writer(self):
        while not self._stop.is_set() or not self._queue.empty():
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if not self._first:
                self._file.write(",\n")
            self._first = False
            self._file.write(json.dumps(event))
            self._file.flush()

    def start_activity(self, tensor_name: str, activity: str):
        self._open_spans.setdefault(tensor_name, []).append(activity)
        self._queue.put({
            "name": activity,
            "cat": tensor_name,
            "ph": "B",
            "ts": self._now_us(),
            "pid": self.rank,
            "tid": tensor_name,
        })

    def end_activity(self, tensor_name: str):
        spans = self._open_spans.get(tensor_name)
        if spans:
            spans.pop()
        self._queue.put({
            "ph": "E",
            "ts": self._now_us(),
            "pid": self.rank,
            "tid": tensor_name,
        })

    def instant(self, name: str):
        self._queue.put({
            "name": name,
            "ph": "i",
            "ts": self._now_us(),
            "pid": self.rank,
            "s": "p",
        })

    def activity(self, name: str):
        """One-shot marker used by the eager op layer."""
        self.instant(name)

    @contextmanager
    def context(self, tensor_name: str, activity: str):
        self.start_activity(tensor_name, activity)
        try:
            yield
        finally:
            self.end_activity(tensor_name)

    def close(self):
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self._file.write("\n]\n")
            self._file.close()
        except ValueError:
            pass


_timeline: Optional[Timeline] = None


def get_timeline() -> Optional[Timeline]:
    return _timeline


def start_timeline(path: str, rank: int = 0) -> Timeline:
    global _timeline
    if _timeline is not None:
        _timeline.close()
    _timeline = Timeline(path, rank)
    return _timeline


def stop_timeline():
    global _timeline
    if _timeline is not None:
        _timeline.close()
        _timeline = None
