"""Chrome-tracing timeline — now a thin exporter over the span tracer.

Reference parity: the C++ ``Timeline``/``TimelineWriter`` pair
(bluefog/common/timeline.{h,cc}) which streams per-op activity spans to
``$BLUEFOG_TIMELINE<rank>.json`` via a dedicated writer thread.  Here the
heavyweight path (device execution) is already traced by ``jax.profiler``;
this module records the *framework-level* activity spans (enqueue, compute,
update phases) with the same file format so the reference's timeline
tooling (chrome://tracing) works unchanged.

The span machinery itself lives in
:class:`bluefog_tpu.observe.tracer.Tracer`; a :class:`Timeline` is a
tracer plus a file-writer **sink** (the writers' ``record(name, tid,
phase)`` surface is exactly the tracer's sink protocol).
``start_timeline`` attaches the writer to the process-global tracer, so
every subsystem that publishes spans — the serving engine, the
resilience runner, the eager op API — lands in the Chrome-trace file
automatically.

Two writer backends:

* **native** (default when buildable) — the C++ lock-free SPSC ring +
  writer thread in ``bluefog_tpu/native/bf_native.cc``, the direct
  equivalent of the reference's boost::lockfree design (timeline.h:65-67).
* **python** — a bounded queue.Queue + thread fallback, always available.
  Like the native ring, the queue REFUSES events when the writer thread
  falls behind (an unbounded queue would trade a bounded trace gap for
  unbounded host memory) and counts the drops; the count flushes to the
  ``bf_timeline_dropped_events`` registry gauge PERIODICALLY (every
  ``BLUEFOG_TIMELINE_FLUSH_EVERY`` writer drains, and whenever the
  queue drains to empty with undisclosed drops) plus once at
  ``close()`` — a long-running saturated writer is visible on the
  metrics side mid-flight, not silently lossy until shutdown.

Set ``BLUEFOG_TIMELINE_NATIVE=0`` to force the Python backend.
"""

from __future__ import annotations

import atexit
import json
import queue
import threading
import time
from contextlib import contextmanager
from typing import Optional

from bluefog_tpu import config as bfconfig
from bluefog_tpu.observe import registry as _obs_registry
from bluefog_tpu.observe import tracer as _obs_tracer

__all__ = ["Timeline", "get_timeline", "start_timeline", "stop_timeline"]

# Python-backend queue bound: ~the native ring's depth.  Override with
# BLUEFOG_TIMELINE_QUEUE_CAPACITY (config.timeline_queue_capacity) for
# stress tests.  (The drop-count flush interval lives in
# config.timeline_flush_every: BLUEFOG_TIMELINE_FLUSH_EVERY,
# default 1024.)


class _PyWriter:
    """Fallback writer: bounded queue.Queue + daemon thread (GIL stands
    in for the native ring's memory ordering; the bound stands in for
    the ring's fixed depth — a full queue drops the event and counts
    it, same contract as the native writer).

    ``on_drop_flush(count)`` is called from the WRITER thread every
    ``BLUEFOG_TIMELINE_FLUSH_EVERY`` drained events — and on any drain
    to empty with new drops — so a saturated queue surfaces on the
    metrics side while the run is still going."""

    def __init__(self, path: str, rank: int, capacity: Optional[int] = None,
                 on_drop_flush=None):
        self.rank = rank
        self._t0 = time.perf_counter()
        if capacity is None:
            capacity = bfconfig.timeline_queue_capacity()
        self._queue: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._dropped = 0
        self._on_drop_flush = on_drop_flush
        # defensive parse (malformed env falls back, never crashes
        # timeline creation)
        self._flush_every = bfconfig.timeline_flush_every()
        self._drained = 0
        self._last_flushed = 0
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._thread.start()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _maybe_flush_drops(self):
        if self._on_drop_flush is None:
            return
        dropped = self._dropped
        if dropped != self._last_flushed:
            self._last_flushed = dropped
            try:
                self._on_drop_flush(dropped)
            except Exception:  # the metrics side must never kill the
                pass           # writer thread

    def _writer(self):
        while not self._stop.is_set() or not self._queue.empty():
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                # idle: disclose any drops accumulated since the last
                # flush (a burst followed by silence must not hide)
                self._maybe_flush_drops()
                continue
            if not self._first:
                self._file.write(",\n")
            self._first = False
            self._file.write(json.dumps(event))
            self._file.flush()
            self._drained += 1
            if self._drained % self._flush_every == 0:
                self._maybe_flush_drops()

    def _put(self, event: dict) -> None:
        try:
            self._queue.put_nowait(event)
        except queue.Full:
            self._dropped += 1

    def record(self, name: str, tid: str, phase: str):
        ts = self._now_us()
        if phase == "B":
            self._put({"name": name, "cat": tid, "ph": "B", "ts": ts,
                       "pid": self.rank, "tid": tid})
        elif phase == "E":
            self._put({"ph": "E", "ts": ts, "pid": self.rank,
                       "tid": tid})
        else:
            self._put({"name": name, "ph": "i", "ts": ts,
                       "pid": self.rank, "s": "p"})

    def dropped(self) -> int:
        return self._dropped

    def close(self):
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self._file.write("\n]\n")
            self._file.close()
        except ValueError:
            pass


def _make_writer(path: str, rank: int, use_native: Optional[bool],
                 on_drop_flush=None):
    if use_native is None:
        use_native = bfconfig.timeline_native()
    if use_native:
        try:
            from bluefog_tpu import native

            if native.available():
                # the native ring flushes its drop count at close() only
                return native.NativeTimelineWriter(path, rank), "native"
        except (ImportError, OSError, RuntimeError) as exc:
            from bluefog_tpu.logging_util import get_logger

            get_logger().warning(
                "native timeline writer unavailable (%s); using the Python "
                "backend", exc)
    return _PyWriter(path, rank, on_drop_flush=on_drop_flush), "python"


class Timeline:
    """A Chrome-trace file fed by a :class:`Tracer`.

    With ``tracer=None`` the timeline owns a private tracer (standalone
    use, e.g. tests); ``start_timeline`` passes the process-global
    tracer instead, making the file a live export of everything the
    framework publishes.  The legacy span surface
    (``start_activity``/``end_activity``/``instant``) forwards to the
    tracer, so existing callers see no change."""

    def __init__(self, path: str, rank: int = 0,
                 use_native: Optional[bool] = None, tracer=None):
        self.path = f"{path}{rank}.json"
        self.rank = rank
        self._writer, self.backend = _make_writer(
            self.path, rank, use_native,
            on_drop_flush=self._flush_dropped_gauge)
        self.tracer = tracer if tracer is not None else _obs_tracer.Tracer(
            pid=rank)
        self.tracer.add_sink(self._writer)
        self._closed = False
        atexit.register(self.close)

    def start_activity(self, tensor_name: str, activity: str):
        self.tracer.begin(tensor_name, activity)

    def end_activity(self, tensor_name: str):
        self.tracer.end(tensor_name)

    def instant(self, name: str):
        self.tracer.instant(name)

    def activity(self, name: str):
        """One-shot marker used by the eager op layer."""
        self.instant(name)

    def dropped_events(self) -> int:
        return self._writer.dropped()

    def _flush_dropped_gauge(self, dropped: int) -> None:
        """Land the drop count in the registry gauge — called
        periodically from the Python writer thread (every
        ``BLUEFOG_TIMELINE_FLUSH_EVERY`` drains) and once at close."""
        if _obs_registry.enabled():
            _obs_registry.get_registry().gauge(
                "bf_timeline_dropped_events",
                "events the timeline writer dropped (saturated queue/ring)",
                rank=self.rank).set(dropped)

    @contextmanager
    def context(self, tensor_name: str, activity: str):
        self.start_activity(tensor_name, activity)
        try:
            yield
        finally:
            self.end_activity(tensor_name)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.tracer.remove_sink(self._writer)
        dropped = self._writer.dropped()
        self._writer.close()
        # flush the FINAL drop count where a dashboard can see it —
        # mid-run flushes only fire every BLUEFOG_TIMELINE_FLUSH_EVERY
        # drains, and the native ring only reports here
        self._flush_dropped_gauge(dropped)


_timeline: Optional[Timeline] = None


def get_timeline() -> Optional[Timeline]:
    return _timeline


def start_timeline(path: str, rank: int = 0) -> Timeline:
    """Open the Chrome-trace file and attach it to the process-global
    tracer: from here on, every published span/instant streams to
    ``<path><rank>.json`` until :func:`stop_timeline`.

    Under ``BLUEFOG_OBSERVE=0`` (checked at start time) the timeline
    binds a PRIVATE tracer instead — span producers fall back to it
    (``observe.tracer.effective_tracer``), so ``BLUEFOG_TIMELINE``
    alone still records the file while the observe layer's global
    buffers and exporters stay empty, honoring the opt-out."""
    global _timeline
    if _timeline is not None:
        _timeline.close()
    tracer = _obs_tracer.get_tracer() if _obs_registry.enabled() else None
    _timeline = Timeline(path, rank, tracer=tracer)
    return _timeline


def stop_timeline():
    global _timeline
    if _timeline is not None:
        _timeline.close()
        _timeline = None
