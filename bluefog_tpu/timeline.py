"""Chrome-tracing timeline.

Reference parity: the C++ ``Timeline``/``TimelineWriter`` pair
(bluefog/common/timeline.{h,cc}) which streams per-op activity spans to
``$BLUEFOG_TIMELINE<rank>.json`` via a dedicated writer thread.  Here the
heavyweight path (device execution) is already traced by ``jax.profiler``;
this module records the *framework-level* activity spans (enqueue, compute,
update phases) with the same file format so the reference's timeline
tooling (chrome://tracing) works unchanged.

Two writer backends:

* **native** (default when buildable) — the C++ lock-free SPSC ring +
  writer thread in ``bluefog_tpu/native/bf_native.cc``, the direct
  equivalent of the reference's boost::lockfree design (timeline.h:65-67).
* **python** — a queue.Queue + thread fallback, always available.

Set ``BLUEFOG_TIMELINE_NATIVE=0`` to force the Python backend.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
from contextlib import contextmanager
from typing import Optional

__all__ = ["Timeline", "get_timeline", "start_timeline", "stop_timeline"]


class _PyWriter:
    """Fallback writer: queue.Queue + daemon thread (GIL stands in for the
    native ring's memory ordering)."""

    def __init__(self, path: str, rank: int):
        self.rank = rank
        self._t0 = time.perf_counter()
        self._queue: "queue.Queue" = queue.Queue()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._first = True
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._thread.start()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _writer(self):
        while not self._stop.is_set() or not self._queue.empty():
            try:
                event = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if not self._first:
                self._file.write(",\n")
            self._first = False
            self._file.write(json.dumps(event))
            self._file.flush()

    def record(self, name: str, tid: str, phase: str):
        ts = self._now_us()
        if phase == "B":
            self._queue.put({"name": name, "cat": tid, "ph": "B", "ts": ts,
                             "pid": self.rank, "tid": tid})
        elif phase == "E":
            self._queue.put({"ph": "E", "ts": ts, "pid": self.rank,
                             "tid": tid})
        else:
            self._queue.put({"name": name, "ph": "i", "ts": ts,
                             "pid": self.rank, "s": "p"})

    def dropped(self) -> int:
        return 0

    def close(self):
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=2.0)
        try:
            self._file.write("\n]\n")
            self._file.close()
        except ValueError:
            pass


def _make_writer(path: str, rank: int, use_native: Optional[bool]):
    if use_native is None:
        use_native = os.environ.get("BLUEFOG_TIMELINE_NATIVE", "1") != "0"
    if use_native:
        try:
            from bluefog_tpu import native

            if native.available():
                return native.NativeTimelineWriter(path, rank), "native"
        except (ImportError, OSError, RuntimeError) as exc:
            from bluefog_tpu.logging_util import get_logger

            get_logger().warning(
                "native timeline writer unavailable (%s); using the Python "
                "backend", exc)
    return _PyWriter(path, rank), "python"


class Timeline:
    def __init__(self, path: str, rank: int = 0,
                 use_native: Optional[bool] = None):
        self.path = f"{path}{rank}.json"
        self.rank = rank
        self._writer, self.backend = _make_writer(self.path, rank, use_native)
        self._lock = threading.Lock()  # writers are single-producer
        self._open_spans = {}
        self._closed = False
        atexit.register(self.close)

    def start_activity(self, tensor_name: str, activity: str):
        with self._lock:
            self._open_spans.setdefault(tensor_name, []).append(activity)
            self._writer.record(activity, tensor_name, "B")

    def end_activity(self, tensor_name: str):
        with self._lock:
            spans = self._open_spans.get(tensor_name)
            if spans:
                spans.pop()
            self._writer.record("", tensor_name, "E")

    def instant(self, name: str):
        with self._lock:
            self._writer.record(name, "", "i")

    def activity(self, name: str):
        """One-shot marker used by the eager op layer."""
        self.instant(name)

    def dropped_events(self) -> int:
        with self._lock:
            return self._writer.dropped()

    @contextmanager
    def context(self, tensor_name: str, activity: str):
        self.start_activity(tensor_name, activity)
        try:
            yield
        finally:
            self.end_activity(tensor_name)

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._writer.close()


_timeline: Optional[Timeline] = None


def get_timeline() -> Optional[Timeline]:
    return _timeline


def start_timeline(path: str, rank: int = 0) -> Timeline:
    global _timeline
    if _timeline is not None:
        _timeline.close()
    _timeline = Timeline(path, rank)
    return _timeline


def stop_timeline():
    global _timeline
    if _timeline is not None:
        _timeline.close()
        _timeline = None
