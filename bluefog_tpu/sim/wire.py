"""Link-cost actor: the virtual wire, priced by the pod's torus.

Each simulated step the ACTIVE (nonzero-weight, healed) edges of the
live mixing round are routed onto the ``PodSpec`` torus; the step's
charge is the bottleneck link's ``load * link_cost * congestion_factor``
— two rank pairs sharing a DCN link serialize, exactly the contention
model ``PodSpec.round_cost`` prices — and every active edge is billed
its own ``pod.round_cost([edge]) * factor * wire_unit`` seconds into the
metrics registry via :func:`~bluefog_tpu.observe.fleet
.record_edge_timing`.  That registry feed is the point: the REAL
:class:`~bluefog_tpu.topology.TopologyControlPlane` reads its windowed
``bf_edge_seconds_total`` deltas from it, so the control plane under
simulation consumes the same telemetry a hardware fleet would emit.

This is the generalization of the adaptive-topology bench's
``VirtualWire`` (which is now a thin wrapper over this class): the
congestion source is an injected ``congestion_fn(step) -> {pair:
factor}`` — typically ``FaultPlan.congested_links`` — instead of a
bound fault plan, and the schedule period for :meth:`p50` is a
constructor argument.  At n=1024 the per-edge billing groups edges by
equal charge into one ``record_edge_timing`` call per value (the
counters land identically; a uniform ring bills in O(distinct costs)
registry calls instead of O(edges)).

The p50 claims are over PERIODS: the mean charge of each complete
``period``-step schedule cycle is one sample (a per-step median of an
alternating cheap-ICI/expensive-DCN series is a knife-edge — whichever
side has one extra sample wins).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["LinkWire"]


class LinkWire:
    """Per-step virtual transport over a ``PodSpec`` torus.

    Args:
      pod: the :class:`~bluefog_tpu.topology.PodSpec` whose torus and
        link costs price the wire.
      registry: the :class:`~bluefog_tpu.observe.MetricsRegistry` the
        per-edge seconds land in (the control plane's telemetry feed).
      schedule_fn: ``step -> DynamicTopology`` — the live round the
        compiled step would play at ``step`` (callers close over their
        control plane's ``active_schedule()``).
      dead_fn: ``() -> dead_mask`` — edges touching dead ranks are
        healed away before billing, like the real exchange.
      congestion_fn: optional ``step -> {(src, dst): factor}`` slowdown
        map (``FaultPlan.congested_links`` has this exact shape).
      wire_unit: virtual seconds per unit of pod cost billed per edge.
      period: schedule period (rounds per cycle) for :meth:`p50`.
    """

    def __init__(self, pod, registry,
                 schedule_fn: Callable[[int], object],
                 dead_fn: Callable[[], object], *,
                 congestion_fn: Optional[Callable[[int], Dict]] = None,
                 wire_unit: float = 1e-3, period: int = 1):
        if period < 1:
            raise ValueError(f"period must be >= 1, got {period}")
        self.pod = pod
        self.registry = registry
        self.schedule_fn = schedule_fn
        self.dead_fn = dead_fn
        self.congestion_fn = congestion_fn
        self.wire_unit = float(wire_unit)
        self.period = int(period)
        self.charges: List[Tuple[int, float]] = []  # (step, cost units)

    def _round_charge(self, pairs, cong) -> float:
        """Bottleneck-link charge of one round: route the active pairs
        onto the torus, scale each link by the worst congestion factor
        of any pair crossing it, take the max ``load * cost * factor``."""
        from bluefog_tpu.topology.torus import link_loads

        loads = link_loads(pairs, self.pod.torus)
        if not loads:
            return 0.0
        fac: Dict = {}
        for p, f in cong.items():
            for k in link_loads([p], self.pod.torus):
                fac[k] = max(fac.get(k, 1.0), float(f))
        return max(load * self.pod.link_cost(k) * fac.get(k, 1.0)
                   for k, load in loads.items())

    def bill(self, step: int) -> float:
        """Bill step ``step``: per-edge seconds into the registry,
        bottleneck charge into ``charges``.  Returns the charge in pod
        cost units (scale by ``wire_unit`` for virtual seconds)."""
        from bluefog_tpu.observe.fleet import record_edge_timing
        from bluefog_tpu.resilience import heal_spec

        spec = heal_spec(self.schedule_fn(step), self.dead_fn())
        cong = (self.congestion_fn(step)
                if self.congestion_fn is not None else {})
        pairs = [e for e, v in zip(spec.edges, spec.edge_weight_values)
                 if v != 0.0]
        # group edges billing the same seconds into one registry call —
        # identical counter values, O(distinct costs) calls
        by_cost: Dict[float, List] = {}
        for e in pairs:
            t = self.pod.round_cost([e]) * cong.get(e, 1.0)
            by_cost.setdefault(t, []).append(e)
        for t, edges in by_cost.items():
            record_edge_timing(None, t * self.wire_unit,
                               registry=self.registry, pairs=edges)
        charge = self._round_charge(pairs, cong)
        self.charges.append((step, charge))
        return charge

    def p50(self, lo: int, hi: int) -> float:
        """Median per-step charge over the complete schedule periods
        inside ``[lo, hi)`` (cost units)."""
        by_step = dict(self.charges)
        period_means = []
        first = (lo + self.period - 1) // self.period
        for p in range(first, hi // self.period):
            steps = range(p * self.period, (p + 1) * self.period)
            if all(s in by_step for s in steps):
                period_means.append(
                    float(np.mean([by_step[s] for s in steps])))
        return (float(np.median(period_means)) if period_means
                else float("nan"))
