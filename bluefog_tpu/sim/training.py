"""Simulated training fleet: the REAL control plane at virtual scale.

The training-side actor owns no model and no optimizer — what it
drives per virtual step is exactly the control-plane stack a real
``run_resilient`` loop drives, unmodified:

* the :class:`~bluefog_tpu.sim.wire.LinkWire` bills the live round's
  healed active edges into ``bf_edge_seconds_total`` (the telemetry
  feed) and returns the bottleneck-link charge;
* the :class:`~bluefog_tpu.observe.fleet.StragglerDetector` folds the
  per-rank virtual step-time vector (base + wire + injected stalls);
* the :class:`~bluefog_tpu.topology.TopologyControlPlane` runs its
  window/patience/margin/probation state machine over those windowed
  deltas and the straggler z snapshot — triggers, synthesizes over the
  calibrated pod, hot-swaps, commits;
* the :class:`~bluefog_tpu.elastic.MembershipController` takes churn
  (``mark_dead``/``admit``/``tick``/``promote``) and re-renders the
  healed + bootstrap-annealed comm weights after every transition and
  every swap — the same ``healing``/``bootstrap`` re-planning a live
  fleet re-delivers to its compiled step.

The step clock is the calibrated cost model: one step costs
``train_step_s`` of device compute plus the wire's bottleneck charge
in virtual seconds, and the fleet advances at the slowest LIVE rank's
pace (lockstep with stalls, the straggler's signature).  Every control
event lands in the shared :class:`~bluefog_tpu.sim.engine.EventLog`
with scalar detail only — byte-stable, digestible.

This is what makes n=1024 claims checkable on one CPU: the eigvals in
``score_active`` are ~0.9 s at 1024 ranks, so a scenario with a
handful of re-plan triggers runs in seconds while every decision —
degraded-window detection, candidate scoring, swap, membership
round-trip — is made by the production code path.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from bluefog_tpu.sim.clock import VirtualClock
from bluefog_tpu.sim.cost import CostModel
from bluefog_tpu.sim.engine import EventLog, Simulation
from bluefog_tpu.sim.traces import ChurnSchedule
from bluefog_tpu.sim.wire import LinkWire

__all__ = ["SimTrainingFleet"]

_SCALARS = (int, float, str, bool, np.integer, np.floating, np.bool_)


class SimTrainingFleet:
    """Virtual-time lockstep training fleet over real control parts.

    Args:
      control: a real :class:`TopologyControlPlane` (typically
        ``synchronous=True`` with a ``candidates_fn`` menu at large n).
      wire: the :class:`LinkWire` billing the control plane's registry;
        its ``schedule_fn`` should close over
        ``control.active_schedule()`` so post-swap billing follows the
        swap — the closed loop.
      membership: optional real :class:`MembershipController`; churn
        actions route through it and weight re-renders come from
        ``comm_weight_arrays()`` (healed + annealed, the real paths).
      straggler: optional real :class:`StragglerDetector` fed the
        per-rank virtual step-time vector each step.
      fault_plan: optional :class:`FaultPlan` supplying per-rank stall
        seconds (congestion enters through the wire's
        ``congestion_fn``, churn through ``churn``).
      churn: optional :class:`ChurnSchedule`; without a membership
        controller ``die`` actions only flip the fleet's dead mask.
      params_fn: optional ``step -> params`` proxy for the control
        plane's probation health checks (``None`` ⇒ probation commits
        on schedule, the r16 default for healthy swaps).
    """

    def __init__(self, *, control, wire: Optional[LinkWire] = None,
                 membership=None, straggler=None, fault_plan=None,
                 churn: Optional[ChurnSchedule] = None,
                 cost: Optional[CostModel] = None,
                 sim: Optional[Simulation] = None,
                 params_fn=None):
        self.control = control
        self.wire = wire
        self.membership = membership
        self.straggler = straggler
        self.fault_plan = fault_plan
        self.churn = churn if churn is not None else ChurnSchedule()
        self.cost = cost if cost is not None else CostModel()
        self.sim = sim if sim is not None else Simulation()
        self.clock: VirtualClock = self.sim.clock
        self.log: EventLog = self.sim.log
        self.params_fn = params_fn
        self.n = control.pod.size
        self._dead = np.zeros(self.n, bool)
        self.step_times: List[Tuple[int, float]] = []
        self.events: List[Tuple[str, int, dict]] = []
        self.weight_renders = 0
        self.step = 0

    # -- views ---------------------------------------------------------- #
    def dead_mask(self) -> np.ndarray:
        if self.membership is not None:
            return np.asarray(self.membership.effective_dead_mask(),
                              bool)
        return self._dead.copy()

    def _record(self, kind: str, step: int, detail: dict,
                actor: str = "") -> None:
        self.events.append((kind, step, detail))
        scalars = {k: v for k, v in detail.items()
                   if isinstance(v, _SCALARS)}
        self.log.record(self.clock.t, kind, actor, step=step,
                        **scalars)

    def _render_weights(self) -> None:
        """Re-deliver comm weights the way a live fleet would: the
        membership controller's healed + bootstrap-annealed render
        when elastic, the plane's healed swap weights otherwise — both
        REAL re-planning paths, counted so scenarios can assert they
        ran."""
        if self.membership is not None:
            self.membership.comm_weight_arrays()
        else:
            from bluefog_tpu.topology.control import swap_comm_weights

            swap_comm_weights(self.control, self.dead_mask())
        self.weight_renders += 1

    def _apply_churn(self, step: int) -> None:
        if self.membership is not None:
            # stamp the virtual step so membership decisions land at
            # the right step in the flight recorder's causal chains
            self.membership.current_step = step
        for a in self.churn.at(step):
            if self.membership is not None:
                if a.action == "die":
                    self.membership.mark_dead(a.rank)
                elif a.action == "admit":
                    self.membership.admit(a.rank)
                elif a.action == "promote":
                    self.membership.promote(a.rank)
            if a.action == "die":
                self._dead[a.rank] = True
            elif a.action == "promote":
                self._dead[a.rank] = False
            self._record(f"membership_{a.action}", step,
                         {"rank": a.rank})
            self._render_weights()

    # -- the loop ------------------------------------------------------- #
    def run(self, steps: int) -> dict:
        for _ in range(steps):
            step = self.step
            self.sim.run(until=self.clock.t)
            self._apply_churn(step)
            if self.membership is not None:
                self.membership.tick()
            dead = self.dead_mask()
            charge = self.wire.bill(step) if self.wire is not None \
                else 0.0
            base = self.cost.train_step_s + self.cost.wire_s(charge)
            per_rank = np.full(self.n, base, np.float64)
            if self.fault_plan is not None:
                per_rank += self.fault_plan.stall_seconds_by_rank(step)
            if self.straggler is not None:
                for r in self.straggler.observe(per_rank):
                    self._record("straggler", step, {"rank": int(r)})
            # the real loop (run_resilient) advances its step counter
            # BEFORE consulting the plane: on_step runs at the step
            # BOUNDARY, so the window that closes at a boundary holds
            # exactly the bills of the steps before it.  Mirror that —
            # it is what makes sim and real trigger on the same step.
            boundary = step + 1
            params = (self.params_fn(boundary)
                      if self.params_fn is not None else None)
            for kind, detail in self.control.on_step(
                    boundary, dead_mask=dead, params=params):
                self._record(kind, boundary, detail)
                if kind in ("topology_swap", "topology_rollback"):
                    if self.membership is not None:
                        self.membership.reschedule(
                            self.control.active_schedule())
                    self._render_weights()
            live = ~dead
            step_s = float(per_rank[live].max()) if live.any() \
                else base
            self.clock.advance(step_s)
            self.step_times.append((step, step_s))
            self.step += 1
        return self.summary()

    # -- claims --------------------------------------------------------- #
    def p50_step_s(self, lo: int, hi: int) -> float:
        """Median virtual step seconds over complete wire periods in
        ``[lo, hi)`` (falls back to a plain median without a wire)."""
        period = self.wire.period if self.wire is not None else 1
        by_step = dict(self.step_times)
        means = []
        first = (lo + period - 1) // period
        for p in range(first, hi // period):
            steps = range(p * period, (p + 1) * period)
            if all(s in by_step for s in steps):
                means.append(float(np.mean([by_step[s]
                                            for s in steps])))
        return float(np.median(means)) if means else float("nan")

    def detect_to_swap(self, onset_step: int) -> dict:
        """Latency from a degradation's onset to the control plane's
        hot-swap: steps and virtual seconds (NaN/None when no swap
        followed the onset)."""
        swap = next((s for k, s, _ in self.events
                     if k == "topology_swap" and s >= onset_step), None)
        if swap is None:
            return {"swap_step": None, "steps": None,
                    "virtual_seconds": float("nan")}
        secs = sum(t for s, t in self.step_times
                   if onset_step <= s <= swap)
        return {"swap_step": int(swap),
                "steps": int(swap - onset_step),
                "virtual_seconds": float(secs)}

    def summary(self) -> dict:
        kinds: Dict[str, int] = {}
        for k, _, _ in self.events:
            kinds[k] = kinds.get(k, 0) + 1
        return {
            "ranks": self.n,
            "steps": self.step,
            "virtual_seconds": self.clock.t,
            "dead": int(self.dead_mask().sum()),
            "active_schedule": self.control.active_name(),
            "weight_renders": self.weight_renders,
            "event_counts": dict(sorted(kinds.items())),
            "events": self.log.n,
            "event_digest": self.log.digest(),
        }
