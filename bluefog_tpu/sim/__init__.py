"""Fleet-scale discrete-event simulation: real control plane, virtual time.

Every scale claim a real-engine bench can make tops out near the host's
core count; this package lifts the ceiling by replacing only the DEVICE
work with a calibrated cost model while the CONTROL decisions stay with
the production code — the same
:class:`~bluefog_tpu.serving.fleet.FleetRouter`,
:class:`~bluefog_tpu.elastic.MembershipController`,
:class:`~bluefog_tpu.observe.fleet.StragglerDetector`, and
:class:`~bluefog_tpu.topology.TopologyControlPlane` a hardware fleet
runs, fed through the same ``observe`` registry families they read in
production.  TACCL's discipline applies (arXiv:2111.04867): the cost
model is calibrated from one measured capture of the real engine, and
the simulator itself is validated against lockstep real-engine runs at
small n — routing decisions bit-equal, dynamics within tolerance —
before any large-n number is quoted (tests/test_sim.py).

* :mod:`~bluefog_tpu.sim.clock` — the one :class:`VirtualClock`
  (deduplicating the benches' private copies);
* :mod:`~bluefog_tpu.sim.engine` — seeded event heap +
  :class:`EventLog` with a streaming SHA-256 (same seed ⇒ byte-equal
  log, O(1) memory at a million events);
* :mod:`~bluefog_tpu.sim.cost` — :class:`CostModel`: committed
  constants for gated runs, ``from_engine`` calibration for validation
  (wall time only through an injected timer — the ``wallclock-in-sim``
  lint rule keeps this package clean of clock reads);
* :mod:`~bluefog_tpu.sim.wire` — :class:`LinkWire`, the torus-priced
  link-cost actor billing ``bf_edge_seconds_total`` (the control
  plane's telemetry feed);
* :mod:`~bluefog_tpu.sim.traces` — request traces and
  :class:`ChurnSchedule` riding ``FaultPlan`` semantics (arrival-time
  generators live in :mod:`bluefog_tpu.benchutil`);
* :mod:`~bluefog_tpu.sim.serving` — :class:`SimReplica` (the serving
  engine's exact host bookkeeping, device work costed) +
  :class:`SimServingFleet` around the real router;
* :mod:`~bluefog_tpu.sim.training` — :class:`SimTrainingFleet` driving
  the real topology control plane / membership / straggler stack at
  n=1024 and beyond.

Guide: docs/simulation.md.  Headline bench: benchmarks/fleet_sim.py.
"""

from bluefog_tpu.sim.clock import VirtualClock  # noqa: F401
from bluefog_tpu.sim.cost import CostModel, measure_step_cost  # noqa: F401
from bluefog_tpu.sim.engine import (  # noqa: F401
    EventLog,
    Simulation,
    format_event,
)
from bluefog_tpu.sim.serving import (  # noqa: F401
    SimReplica,
    SimRequest,
    SimServingFleet,
)
from bluefog_tpu.sim.traces import (  # noqa: F401
    ChurnAction,
    ChurnSchedule,
    RequestTrace,
)
from bluefog_tpu.sim.training import SimTrainingFleet  # noqa: F401
from bluefog_tpu.sim.wire import LinkWire  # noqa: F401

__all__ = [
    "VirtualClock",
    "EventLog",
    "Simulation",
    "format_event",
    "CostModel",
    "measure_step_cost",
    "LinkWire",
    "RequestTrace",
    "ChurnAction",
    "ChurnSchedule",
    "SimRequest",
    "SimReplica",
    "SimServingFleet",
    "SimTrainingFleet",
]
