"""Trace layer: request traces and churn schedules for fleet sims.

Arrival *time* generation lives in :mod:`bluefog_tpu.benchutil`
(``poisson_arrivals`` / ``diurnal_arrivals`` / ``flash_crowd_arrivals``
— seeded, property-tested); this module shapes those timestamps into
full request traces (prompt lengths, decode budgets, deadlines) and
turns fault semantics into explicit membership churn schedules.

Churn rides the repo's existing fault vocabulary rather than inventing
one: :meth:`ChurnSchedule.from_fault_plan` derives ``die`` actions from
``FaultPlan.dead_ranks`` deltas and ``admit``/``promote`` rejoin
actions for ``rejoinable_ranks``, so the same deterministic plan object
that drives a real chaos run drives the simulated membership
controller.  Everything is a pure function of its seed/plan — no
wall-clock reads, no unseeded randomness.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["RequestTrace", "ChurnAction", "ChurnSchedule"]


@dataclasses.dataclass(frozen=True)
class RequestTrace:
    """A seeded request workload: ``arrivals[i]`` virtual seconds,
    ``prompt_lens[i]`` prompt tokens, ``budgets[i]`` max new tokens
    (and optional absolute ``deadlines[i]``)."""

    arrivals: np.ndarray
    prompt_lens: np.ndarray
    budgets: np.ndarray
    deadlines: Optional[np.ndarray] = None

    def __post_init__(self):
        n = self.arrivals.shape[0]
        for name in ("prompt_lens", "budgets"):
            a = getattr(self, name)
            if a.shape[0] != n:
                raise ValueError(f"{name} has {a.shape[0]} entries for "
                                 f"{n} arrivals")
        if self.deadlines is not None and self.deadlines.shape[0] != n:
            raise ValueError("deadlines length mismatch")

    @property
    def n(self) -> int:
        return int(self.arrivals.shape[0])

    @classmethod
    def build(cls, arrivals, *, seed: int,
              prompt_len: Tuple[int, int] = (4, 16),
              new_tokens: Tuple[int, int] = (4, 16),
              deadline_slack: Optional[float] = None) -> "RequestTrace":
        """Draw lengths/budgets from ``RandomState(seed)`` uniformly in
        the inclusive ranges (the shape the serving benches use); with
        ``deadline_slack`` each request gets an absolute deadline
        ``arrival + slack``."""
        arrivals = np.asarray(arrivals, np.float64)
        rs = np.random.RandomState(seed)
        n = arrivals.shape[0]
        lens = rs.randint(prompt_len[0], prompt_len[1] + 1,
                          n).astype(np.int64)
        budgets = rs.randint(new_tokens[0], new_tokens[1] + 1,
                             n).astype(np.int64)
        deadlines = (arrivals + float(deadline_slack)
                     if deadline_slack is not None else None)
        return cls(arrivals=arrivals, prompt_lens=lens,
                   budgets=budgets, deadlines=deadlines)


@dataclasses.dataclass(frozen=True, order=True)
class ChurnAction:
    """One membership transition at a virtual step: ``die`` (LIVE →
    DEAD through ``mark_dead``), ``admit`` (DEAD → JOINING), or
    ``promote`` (JOINING → LIVE) — the real controller's verbs."""

    step: int
    rank: int
    action: str

    _ACTIONS = ("die", "admit", "promote")

    def __post_init__(self):
        if self.action not in self._ACTIONS:
            raise ValueError(f"unknown churn action {self.action!r} "
                             f"(one of {self._ACTIONS})")


class ChurnSchedule:
    """A deterministic, step-indexed list of membership transitions."""

    def __init__(self, actions: Sequence[ChurnAction] = ()):
        self.actions: Tuple[ChurnAction, ...] = tuple(
            sorted(actions))

    def at(self, step: int) -> List[ChurnAction]:
        """Actions due exactly at ``step`` (drivers apply them before
        the step's control-plane tick — membership transitions are
        structural, they bypass patience)."""
        return [a for a in self.actions if a.step == step]

    @property
    def ranks(self) -> List[int]:
        return sorted({a.rank for a in self.actions})

    @classmethod
    def from_fault_plan(cls, plan, steps: int, *,
                        admit_after: int = 0,
                        promote_after: int = 16) -> "ChurnSchedule":
        """Derive churn from a :class:`~bluefog_tpu.resilience.faults
        .FaultPlan`: a rank entering ``dead_ranks``/``preempted_ranks``
        at step *s* dies at *s*; the first step a rank shows up in
        ``rejoinable_ranks`` (its preempt window ended, nothing else
        holds it) it is admitted ``admit_after`` steps later and
        promoted ``promote_after`` steps after that — the sim ticks the
        controller's bootstrap anneal in between.  One admission per
        rank (re-preemption after a rejoin emits a fresh ``die`` but no
        second rejoin — keep plans simple enough to read)."""
        actions: List[ChurnAction] = []
        prev: set = set()
        admitted: set = set()
        for s in range(steps):
            down = set(int(r) for r in plan.dead_ranks(s))
            down |= set(int(r) for r in plan.preempted_ranks(s))
            for r in sorted(down - prev):
                actions.append(ChurnAction(s, r, "die"))
            for r in sorted(set(int(r) for r in
                                plan.rejoinable_ranks(s)) - admitted):
                s_admit = s + int(admit_after)
                s_promote = s_admit + int(promote_after)
                if s_admit < steps:
                    actions.append(ChurnAction(s_admit, r, "admit"))
                if s_promote < steps:
                    actions.append(ChurnAction(s_promote, r, "promote"))
                admitted.add(r)
            prev = down
        return cls(actions)
