"""Simulated serving replicas + fleet driver around the REAL router.

The scale bottleneck in the serving benches is the model forward, not
the control plane — so :class:`SimReplica` keeps the
:class:`~bluefog_tpu.serving.engine.ServingEngine`'s exact host
bookkeeping (the same :class:`~bluefog_tpu.serving.scheduler
.FifoScheduler`, the same LIFO slot pool discipline, the same
admit → chunked-prefill → decode-horizon step order, the same metric
publication points) and deletes only the device work, charging the
calibrated :class:`~bluefog_tpu.sim.cost.CostModel` instead.  Every
family lands in the replica's own
:class:`~bluefog_tpu.observe.MetricsRegistry` under the names the real
:class:`~bluefog_tpu.serving.metrics.ServingMetrics` uses —
``bf_serving_slot_occupancy``, ``bf_serving_queue_depth``,
``bf_serving_ttft_seconds``, ``bf_serving_last_step_ts``, … — which is
what makes the REAL :class:`~bluefog_tpu.serving.fleet.FleetRouter`
drive simulated fleets unmodified: its gossip scrapes those exact
gauges.  With the same clock, trace, and router configuration, the
sim's routing decisions are BIT-EQUAL to a lockstep real-engine run
(tests/test_sim.py asserts it at 3 replicas).

Unlike :class:`~bluefog_tpu.serving.metrics.ServingMetrics`, the sim's
metrics shim keeps NO per-request records — per-request state lives on
the :class:`SimRequest` itself and percentile families are the
registry's windowed histograms — so a million-request trace holds
memory at O(fleet), not O(requests).

:class:`SimServingFleet` is the lockstep driver: every live replica
steps each tick (``cost.step_s`` virtual seconds), arrivals due by the
tick are routed through one held router snapshot (one gossip amortized
over the tick's admissions, the router's documented batch idiom), the
clock idle-jumps to the next arrival when the fleet drains, and
replica death evacuates residents token-exact through the router's
dead-masked walk — the same failover the chaos bench measures, at
fleet sizes it cannot reach.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bluefog_tpu.serving.scheduler import FifoScheduler, RequestRejected
from bluefog_tpu.sim.clock import VirtualClock
from bluefog_tpu.sim.cost import CostModel
from bluefog_tpu.sim.engine import EventLog, Simulation

__all__ = ["SimRequest", "SimReplica", "SimServingFleet"]

# request states — the serving engine's exact vocabulary
# (bluefog_tpu/serving/engine.py), so event logs and ``retired_total``
# outcome labels read identically across sim and real runs
QUEUED, PREFILL, DECODE = "queued", "prefill", "decode"
COMPLETED, CANCELLED, REJECTED = "completed", "cancelled", "rejected"
FAILOVER = "failover"


class SimRequest:
    """One simulated request: the engine's host-visible request state
    without token values (lengths drive every control decision — the
    tokens themselves never influenced routing, admission, or
    retirement except through EOS, which a trace models as a budget)."""

    __slots__ = ("rid", "prompt_len", "max_new_tokens", "deadline",
                 "state", "slot", "n_tokens", "submit_t",
                 "first_token_t", "finish_t", "_prefill_pos", "_cancel")

    def __init__(self, rid, prompt_len: int, max_new_tokens: int,
                 deadline: Optional[float] = None):
        if prompt_len < 1:
            raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.rid = rid
        self.prompt_len = int(prompt_len)
        self.max_new_tokens = int(max_new_tokens)
        self.deadline = deadline
        self.state = QUEUED
        self.slot: Optional[int] = None
        self.n_tokens = 0
        self.submit_t: Optional[float] = None
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self._prefill_pos = 0
        self._cancel = False

    @property
    def done(self) -> bool:
        return self.state in (COMPLETED, CANCELLED, REJECTED)


class _SimMetrics:
    """Record-free twin of :class:`~bluefog_tpu.serving.metrics
    .ServingMetrics`: identical registry families (names, help text,
    labels), O(1) state.  Exposes ``_registry`` because that is the
    attribute :class:`FleetRouter` reads off ``engine.metrics``."""

    def __init__(self, registry):
        self._registry = registry
        self.n_rejected = 0
        self.n_failovers = 0
        self.last_step_ts: Optional[float] = None

    def on_submit(self, now: float):
        self._registry.counter("bf_serving_requests_total",
                               "requests submitted").inc()

    def on_reject(self, now: float):
        self.n_rejected += 1
        self._registry.counter(
            "bf_serving_rejected_total",
            "requests refused (backpressure or too long)").inc()

    def on_admit(self, now: float):
        pass  # the real shim's admit work is span bookkeeping only

    def on_first_token(self, req: SimRequest, now: float):
        self._registry.histogram("bf_serving_ttft_seconds",
                                 "submit -> first token").observe(
                                     now - req.submit_t)
        self._registry.counter("bf_serving_tokens_total",
                               "tokens generated").inc()

    def on_tokens(self, n: int):
        """Batch form of ``on_token`` — ``n`` non-first tokens this
        step (counters add; one inc per slot-step, not per token)."""
        if n > 0:
            self._registry.counter("bf_serving_tokens_total",
                                   "tokens generated").inc(n)

    def on_retire(self, req: SimRequest, now: float, outcome: str):
        req.finish_t = now
        self._registry.counter("bf_serving_retired_total",
                               "requests retired", outcome=outcome).inc()
        self._registry.histogram("bf_serving_latency_seconds",
                                 "submit -> retire").observe(
                                     now - req.submit_t)

    def on_failover(self, now: float):
        self.n_failovers += 1
        self._registry.counter(
            "bf_serving_failovers_total",
            "requests handed off to another replica").inc()

    def on_prefill_chunk(self):
        self._registry.counter("bf_serving_prefill_chunks_total",
                               "cold prefill chunks computed").inc()

    def on_step(self, occupancy: float, queue_depth: int,
                step_seconds: Optional[float] = None,
                now: Optional[float] = None):
        reg = self._registry
        reg.counter("bf_serving_steps_total", "engine steps").inc()
        reg.gauge("bf_serving_slot_occupancy",
                  "active slots / capacity, last step").set(occupancy)
        reg.gauge("bf_serving_queue_depth",
                  "queued requests, last step").set(queue_depth)
        if now is not None:
            self.last_step_ts = now
            reg.gauge("bf_serving_last_step_ts",
                      "engine-clock time of the last step").set(now)
        if step_seconds is not None:
            reg.histogram("bf_step_wall_seconds",
                          "train/engine step wall time",
                          loop="serving").observe(step_seconds)


class SimReplica:
    """One simulated serving replica — the engine's host bookkeeping
    with the device work replaced by the cost model (module docs)."""

    def __init__(self, name: str, *, capacity: int, max_len: int,
                 prefill_chunk: int = 32, decode_horizon: int = 1,
                 prefill_budget: int = 1, max_queue: int = 64,
                 clock: Optional[VirtualClock] = None,
                 cost: Optional[CostModel] = None,
                 registry=None):
        from bluefog_tpu.observe import MetricsRegistry

        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.name = str(name)
        self.capacity = int(capacity)
        self.max_len = int(max_len)
        self.prefill_chunk = int(prefill_chunk)
        self.decode_horizon = int(decode_horizon)
        self.prefill_budget = int(prefill_budget)
        self.clock = clock if clock is not None else VirtualClock()
        self.cost = cost if cost is not None else CostModel()
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        self.metrics = _SimMetrics(self.registry)
        self.scheduler = FifoScheduler(max_queue=max_queue)
        # LIFO slot pool, identical discipline to KVSlotPool: initial
        # allocs ascend 0,1,2…; a freed slot is reused first
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._running: Dict[int, SimRequest] = {}
        self._admitting: Optional[SimRequest] = None
        self.dead = False
        self.reject_submits = False
        self.n_steps = 0

    # -- state views --------------------------------------------------- #
    @property
    def n_free(self) -> int:
        return len(self._free)

    def occupancy(self) -> float:
        return (self.capacity - len(self._free)) / self.capacity

    @property
    def busy(self) -> bool:
        return bool(self._running or self._admitting
                    or self.scheduler.queue_depth)

    # -- the engine surface the router uses ----------------------------- #
    def submit(self, request: SimRequest) -> SimRequest:
        """Mirror of ``ServingEngine.submit``: ``ValueError`` for a
        request no slot can ever hold, :class:`RequestRejected` for
        backpressure (and for a dead/fault-rejecting replica — the
        walk-through signal the router falls through on)."""
        total = request.prompt_len + request.max_new_tokens
        if total > self.max_len:
            request.state = REJECTED
            self.metrics.on_reject(self.clock())
            raise ValueError(
                f"request needs {total} cache positions but slots hold "
                f"{self.max_len} (prompt {request.prompt_len} + "
                f"max_new_tokens {request.max_new_tokens})")
        now = self.clock()
        if self.dead or self.reject_submits:
            self.metrics.on_reject(now)
            raise RequestRejected(
                "replica dead" if self.dead else "replica rejecting",
                queue_depth=self.scheduler.queue_depth,
                max_queue=self.scheduler.max_queue)
        try:
            self.scheduler.submit(request)
        except RequestRejected:
            request.state = REJECTED
            self.metrics.on_reject(now)
            raise
        request.state = QUEUED
        request.submit_t = now
        self.metrics.on_submit(now)
        return request

    # -- the serving loop ---------------------------------------------- #
    def step(self) -> bool:
        """One engine iteration, the real step's exact order: shed and
        cancel, admit + budgeted prefill chunks, decode one horizon for
        every active slot, publish the step gauges.  Device time is the
        DRIVER's to charge (``cost.step_s`` per lockstep tick)."""
        now = self.clock()
        # 1. deadline shedding in the queue
        for req in self.scheduler.expire(now):
            req.state = CANCELLED
            self.metrics.on_retire(req, now, CANCELLED)
        # 2. running cancellations (explicit or deadline)
        live = list(self._running.values())
        if self._admitting is not None:
            live.append(self._admitting)
        for req in live:
            if req._cancel or (req.deadline is not None
                               and now >= req.deadline):
                self._retire(req, CANCELLED, now)
        # 3+4. admission + chunked prefill under the per-step budget
        chunks = 0
        while chunks < self.prefill_budget:
            if self._admitting is None:
                if not self._free:
                    break
                req = self.scheduler.admit(now)
                if req is None:
                    break
                req.slot = self._free.pop()
                self.metrics.on_admit(now)
                n_ctx = req.prompt_len + req.n_tokens
                if n_ctx > 1:
                    req.state = PREFILL
                    self._admitting = req
                else:  # single-token prompt: straight to decode
                    req.state = DECODE
                    self._running[req.slot] = req
                    continue
            self._prefill_one_chunk(self._admitting)
            chunks += 1
        # 5. one decode horizon for every active slot
        decoding = [r for r in self._running.values()
                    if r.state == DECODE]
        if decoding:
            now2 = self.clock()
            for req in decoding:
                emitted = 0
                for _ in range(self.decode_horizon):
                    first = req.n_tokens == 0
                    req.n_tokens += 1
                    if first:
                        req.first_token_t = now2
                        self.metrics.on_first_token(req, now2)
                    else:
                        emitted += 1
                    if req.n_tokens >= req.max_new_tokens:
                        self._retire(req, COMPLETED, now2)
                        break
                self.metrics.on_tokens(emitted)
        self.n_steps += 1
        self.metrics.on_step(self.occupancy(),
                             self.scheduler.queue_depth,
                             self.cost.step_s, now=now)
        return self.busy

    def _prefill_one_chunk(self, req: SimRequest) -> None:
        n_prefill = req.prompt_len + req.n_tokens - 1
        valid = min(self.prefill_chunk, n_prefill - req._prefill_pos)
        self.metrics.on_prefill_chunk()
        req._prefill_pos += valid
        if req._prefill_pos < n_prefill:
            return
        self._admitting = None
        self._running[req.slot] = req
        req.state = DECODE

    def _retire(self, req: SimRequest, outcome: str,
                now: float) -> None:
        if req is self._admitting:
            self._admitting = None
        if req.slot is not None:
            self._running.pop(req.slot, None)
            self._free.append(req.slot)
            req.slot = None
        req.state = outcome
        self.metrics.on_retire(req, now, outcome)

    # -- failover ------------------------------------------------------- #
    def evacuate(self) -> List[SimRequest]:
        """Replica death: hand every unfinished resident (queued,
        prefilling, decoding) back to the driver with its emitted-token
        count intact — the token-exact failover contract.  Residents
        that held a slot retire here with outcome ``failover``; each
        departing request counts one ``bf_serving_failovers_total``."""
        now = self.clock()
        out: List[SimRequest] = []
        for req in self.scheduler.drain():
            req.state = FAILOVER
            self.metrics.on_failover(now)
            out.append(req)
        residents = list(self._running.values())
        if self._admitting is not None:
            residents.append(self._admitting)
        for req in residents:
            self.metrics.on_failover(now)
            self._retire(req, FAILOVER, now)
            req._prefill_pos = 0  # the inheriting replica replays
            # prefill over (prompt ‖ tokens)[:-1], like a real resume
            out.append(req)
        return out


class SimServingFleet:
    """Lockstep fleet driver around the real router (module docs)."""

    def __init__(self, replicas: Sequence[SimReplica], *,
                 cost: Optional[CostModel] = None,
                 sim: Optional[Simulation] = None,
                 fault_plan=None,
                 router=None, router_kwargs: Optional[dict] = None,
                 poll_every: int = 1, blackbox=None):
        from bluefog_tpu.serving.fleet import FleetRouter

        if not replicas:
            raise ValueError("SimServingFleet needs >= 1 replica")
        if poll_every < 1:
            raise ValueError(f"poll_every must be >= 1, got {poll_every}")
        self.replicas = list(replicas)
        clocks = {id(r.clock) for r in self.replicas}
        if len(clocks) != 1:
            raise ValueError("replicas must share one VirtualClock")
        self.clock: VirtualClock = self.replicas[0].clock
        self.cost = cost if cost is not None else self.replicas[0].cost
        self.sim = sim if sim is not None else Simulation(
            clock=self.clock)
        if self.sim.clock is not self.clock:
            raise ValueError("simulation and replicas must share one "
                             "VirtualClock")
        self.log: EventLog = self.sim.log
        self.fault_plan = fault_plan
        if router is None:
            kw = dict(router_kwargs or {})
            kw.setdefault("clock", self.clock)
            # seeded-backoff sleeps burn VIRTUAL seconds
            kw.setdefault("sleep", self.clock.advance)
            kw.setdefault("blackbox", blackbox)
            router = FleetRouter(self.replicas, **kw)
        self.router = router
        # scrape cadence in ticks: 1 re-polls every arrival tick (the
        # bit-equal-lockstep default); >1 amortizes one gossip scrape
        # over that many ticks' admissions — the router's documented
        # batch idiom, and what makes a million-request trace cheap
        # (the scrape's percentile walk is the sim's hot path)
        self.poll_every = int(poll_every)
        self.blackbox = blackbox
        self.tick = 0
        self.polls = 0
        self.lost = 0
        self.failovers = 0

    def _decide(self, kind, **detail):
        from bluefog_tpu.observe import blackbox as _blackbox

        return _blackbox.record_decision(
            "sim_serving", kind, step=self.tick,
            blackbox=self.blackbox, detail=detail or None)

    # -- fleet views ---------------------------------------------------- #
    def dead_mask(self) -> np.ndarray:
        return np.array([r.dead for r in self.replicas], bool)

    def _poll(self):
        snap = self.router.poll(dead_mask=self.dead_mask())
        self.polls += 1
        if self.cost.gossip_round_s:
            self.clock.advance(self.cost.poll_s(snap.rounds))
        return snap

    # -- fault-plan application ----------------------------------------- #
    def _apply_faults(self, tick: int) -> List[float]:
        """Apply ``ServingFaultPlan`` state for this tick: death
        transitions (with token-exact evacuation + re-route), revivals,
        submit-rejection windows.  Returns per-replica stall seconds —
        a stalled replica skips this tick's step (its heartbeat
        freezes; staleness is the router's to judge)."""
        stalls = [0.0] * len(self.replicas)
        plan = self.fault_plan
        if plan is None:
            return stalls
        for i, r in enumerate(self.replicas):
            dead = bool(plan.is_dead(i, tick))
            if dead and not r.dead:
                self._kill(i)
            elif r.dead and not dead:
                r.dead = False  # revived: empty, cold, routable again
                self.log.record(self.clock.t, "replica_up", r.name)
            r.reject_submits = bool(plan.rejects_submit(i, tick))
            stalls[i] = float(plan.stall_seconds(i, tick))
        return stalls

    def _kill(self, idx: int) -> None:
        r = self.replicas[idx]
        residents = r.evacuate()
        r.dead = True
        self.log.record(self.clock.t, "replica_down", r.name,
                        evacuated=len(residents))
        if not residents:
            return
        snap = self._poll()  # fresh dead-masked view for the re-route
        for req in residents:
            try:
                j, _ = self.router.submit(req, snapshot=snap,
                                          dead_mask=self.dead_mask())
            except RequestRejected:
                self.lost += 1
                self.log.record(self.clock.t, "lost", rid=req.rid)
                self._decide("lost", rid=int(req.rid), replica=r.name)
            else:
                self.failovers += 1
                self.log.record(self.clock.t, "failover",
                                self.replicas[j].name, rid=req.rid)
                self._decide("failover", rid=int(req.rid),
                             to=self.replicas[j].name)

    # -- the run loop --------------------------------------------------- #
    def run(self, trace, *, max_ticks: Optional[int] = None) -> dict:
        """Drive ``trace`` to completion (or ``max_ticks``): per tick —
        deliver due scheduled events, apply the fault plan, route every
        arrival due by now against ONE held router snapshot (refreshed
        at most every ``poll_every`` clock advances), then step every
        live unstalled replica in lockstep and advance the clock by the
        calibrated step cost.  An idle fleet jumps straight to the next
        arrival."""
        arrivals = trace.arrivals
        n = trace.n
        i = 0
        snap = None
        snap_age = self.poll_every  # the first arrival polls fresh
        while True:
            self.sim.run(until=self.clock.t)
            stalls = self._apply_faults(self.tick)
            if i < n and arrivals[i] <= self.clock.t:
                if snap is None or snap_age >= self.poll_every:
                    snap = self._poll()
                    snap_age = 0
                while i < n and arrivals[i] <= self.clock.t:
                    req = SimRequest(
                        i, int(trace.prompt_lens[i]),
                        int(trace.budgets[i]),
                        deadline=(float(trace.deadlines[i])
                                  if trace.deadlines is not None
                                  else None))
                    try:
                        j, _ = self.router.submit(
                            req, snapshot=snap,
                            dead_mask=self.dead_mask())
                    except RequestRejected:
                        self.lost += 1
                        self.log.record(self.clock.t, "lost", rid=i)
                        self._decide("lost", rid=int(i))
                    else:
                        self.log.record(self.clock.t, "route",
                                        self.replicas[j].name, rid=i)
                    i += 1
            if not any(r.busy for r in self.replicas if not r.dead):
                if i >= n:
                    break
                self.clock.jump_to(float(arrivals[i]))
                snap_age += 1
                continue
            # a stalled replica holds its work but skips the tick — its
            # heartbeat freezes while the stall window's ticks elapse
            for k, r in enumerate(self.replicas):
                if not r.dead and stalls[k] <= 0.0:
                    r.step()
            self.clock.advance(self.cost.step_s)
            snap_age += 1
            self.tick += 1
            if max_ticks is not None and self.tick >= max_ticks:
                break
        return self.summary()

    # -- summaries ------------------------------------------------------ #
    def _sum_counter(self, name: str, **labels) -> float:
        total = 0.0
        for r in self.replicas:
            for n_, kind, _h, lab, m in r.registry.collect():
                if n_ == name and kind == "counter" and all(
                        lab.get(k) == v for k, v in labels.items()):
                    total += m.value
        return total

    def _merged_percentile(self, name: str, q: float) -> float:
        from bluefog_tpu.observe.registry import percentile

        values: List[float] = []
        for r in self.replicas:
            for n_, kind, _h, _lab, m in r.registry.collect():
                if n_ == name and kind == "histogram":
                    values.extend(m.window_values)
        return percentile(values, q)

    def summary(self) -> dict:
        """Fleet totals from the same registry families an exporter
        would scrape (percentiles are over the histograms' retained
        windows — recent-biased by design at million-request scale)."""
        t = self.clock.t
        tokens = self._sum_counter("bf_serving_tokens_total")
        return {
            "replicas": len(self.replicas),
            "ticks": self.tick,
            "virtual_seconds": t,
            "routed": self.router.n_routed,
            "saturated": self.router.n_saturated,
            "lost_requests": self.lost,
            "failovers": self.failovers,
            "polls": self.polls,
            "submitted": self._sum_counter("bf_serving_requests_total"),
            "completed": self._sum_counter("bf_serving_retired_total",
                                           outcome=COMPLETED),
            "cancelled": self._sum_counter("bf_serving_retired_total",
                                           outcome=CANCELLED),
            "tokens_total": tokens,
            "tokens_per_vsec": tokens / t if t > 0 else 0.0,
            "ttft_p50_vs": self._merged_percentile(
                "bf_serving_ttft_seconds", 50),
            "ttft_p99_vs": self._merged_percentile(
                "bf_serving_ttft_seconds", 99),
            "latency_p50_vs": self._merged_percentile(
                "bf_serving_latency_seconds", 50),
            "events": self.log.n,
            "event_digest": self.log.digest(),
        }
