"""Calibrated cost model: virtual seconds per unit of real work.

TACCL's lesson (arXiv:2111.04867) applies to simulators as much as to
schedule synthesis: a cost model is only trustworthy when it is
anchored to measured executions.  :class:`CostModel` holds the handful
of per-operation costs a fleet simulation charges —

* ``step_s`` — one serving-engine iteration under full decode slots
  (the lockstep tick cost every busy replica pays);
* ``prefill_chunk_s`` — one cold prefill chunk (a model forward over
  one chunk; the engine budgets prefill work per step, so the sim
  prices it the same way);
* ``gossip_round_s`` — one fleet push-sum gossip round (the router's
  ``poll`` converges in a measured number of rounds; the sim charges
  ``rounds * gossip_round_s`` per poll);
* ``train_step_s`` — one training step's device compute, EXCLUDING the
  wire (the link-cost actor bills the wire per active edge);
* ``wire_unit_s`` — virtual seconds per unit of ``PodSpec`` round cost
  (per-link pricing stays in ``PodSpec``: the sim multiplies its
  contention-priced cost units by this scale, the same convention the
  adaptive-topology bench's virtual wire established);
* ``a2a_unit_s`` — virtual seconds per unit of all-to-all dispatch
  cost (``compile_all_to_all``'s per-round charges).  Separate from
  ``wire_unit_s`` because expert dispatch moves activations, not
  parameter deltas: its payload scales with tokens per step, so its
  calibration anchor differs from the mixing wire's.

Two ways to get one:

* **Committed constants** (the default construction): the gated
  ``fleet_sim`` bench runs on a frozen model so its event-log digest
  and headline numbers are cross-host deterministic and gateable.
* **Measured** (:meth:`CostModel.from_engine` /
  :func:`measure_step_cost`): one capture of the real engine — the
  calibration workflow docs/simulation.md describes, used by the
  validation tests so sim and real runs share one measured timebase.

Calibration is the one place the sim touches wall time, and it does so
only through an INJECTED ``timer`` callable (callers pass
``time.perf_counter``).  There is deliberately no default: sim code
takes no wall-clock reads (the ``wallclock-in-sim`` lint rule), so the
caller owning the measurement owns the timer.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence

import numpy as np

__all__ = ["CostModel", "measure_step_cost"]


def measure_step_cost(engine, prompts: Sequence, *,
                      timer: Callable[[], float],
                      new_tokens: int = 32,
                      warmup: int = 3, reps: int = 12) -> float:
    """Median wall seconds of one real engine step under FULL slots —
    the per-tick device cost a simulated replica charges.  ``timer``
    must be injected (e.g. ``time.perf_counter``); the sim package
    itself never reads the wall clock."""
    if timer is None:
        raise ValueError(
            "measure_step_cost needs an injected timer (e.g. "
            "time.perf_counter) — sim code takes no wall-clock reads")
    from bluefog_tpu.serving.engine import Request

    for p in prompts:
        engine.submit(Request(prompt=np.asarray(p, np.int32),
                              max_new_tokens=int(new_tokens)))
    for _ in range(warmup):
        engine.step()
    samples = []
    for _ in range(reps):
        t0 = timer()
        engine.step()
        samples.append(timer() - t0)
    return float(np.median(samples))


@dataclasses.dataclass(frozen=True)
class CostModel:
    """Virtual seconds per unit of simulated work (see module docs).
    Frozen: a run's costs are part of its deterministic identity — the
    event-log digest is only meaningful against a fixed model."""

    step_s: float = 2e-3
    prefill_chunk_s: float = 1e-3
    gossip_round_s: float = 1e-4
    train_step_s: float = 1e-3
    wire_unit_s: float = 1e-3
    a2a_unit_s: float = 1e-3

    def __post_init__(self):
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if not (v >= 0.0):
                raise ValueError(f"{f.name} must be >= 0, got {v}")

    # -- charges -------------------------------------------------------- #
    def poll_s(self, rounds: int) -> float:
        """One router poll: the gossip converged in ``rounds`` push-sum
        rounds (the snapshot records it)."""
        return float(rounds) * self.gossip_round_s

    def wire_s(self, cost_units: float) -> float:
        """Convert ``PodSpec`` contention-priced cost units (a round's
        bottleneck-link charge) into virtual seconds."""
        return float(cost_units) * self.wire_unit_s

    def a2a_s(self, cost_units: float) -> float:
        """Convert an all-to-all dispatch round's ``PodSpec`` cost
        units (``compile_all_to_all`` pricing) into virtual seconds."""
        return float(cost_units) * self.a2a_unit_s

    # -- calibration ---------------------------------------------------- #
    @classmethod
    def from_engine(cls, engine, prompts: Sequence, *,
                    timer: Callable[[], float],
                    new_tokens: int = 32, warmup: int = 3,
                    reps: int = 12, **overrides) -> "CostModel":
        """Calibrate ``step_s`` (and, absent overrides,
        ``prefill_chunk_s`` — one chunk is one bounded forward, same
        order as a full-slot step) from ONE measured capture of the
        real engine; remaining fields keep their committed defaults
        unless overridden."""
        step_s = measure_step_cost(engine, prompts, timer=timer,
                                   new_tokens=new_tokens,
                                   warmup=warmup, reps=reps)
        fields = {"step_s": step_s,
                  "prefill_chunk_s": overrides.pop("prefill_chunk_s",
                                                   step_s)}
        fields.update(overrides)
        return cls(**fields)
