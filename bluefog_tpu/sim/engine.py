"""Seeded discrete-event core: event heap, virtual clock, byte-stable log.

The engine is the small deterministic kernel under every fleet-scale
simulation in this repo: a priority queue of ``(time, seq)``-ordered
events, a :class:`~bluefog_tpu.sim.clock.VirtualClock` that only moves
when an event (or a lockstep driver) moves it, and an :class:`EventLog`
whose lines are formatted byte-stably and folded into a running SHA-256
— the "same seed ⇒ byte-equal event log" acceptance check costs O(1)
memory even across a million-request trace.

Two usage shapes coexist:

* **Heap-driven**: schedule callbacks with :meth:`Simulation.at` /
  :meth:`Simulation.after` and :meth:`Simulation.run` them in time
  order — churn, congestion windows, and flash crowds are this shape.
* **Lockstep**: a fleet driver advances the shared clock itself (every
  busy replica steps per tick, exactly like the real lockstep benches)
  and calls :meth:`Simulation.run` with ``until=clock.t`` between ticks
  to deliver any control events that came due.

Both log through the same :class:`EventLog`, so a mixed run still has
one totally ordered record.  No wall-clock reads, no unseeded
randomness: ``Simulation.rng`` is the only entropy source, and ties are
broken by insertion sequence — a heap pop order that is a pure function
of the schedule calls.
"""

from __future__ import annotations

import hashlib
import heapq
from typing import Callable, List, Optional, Tuple

import numpy as np

from bluefog_tpu.sim.clock import VirtualClock

__all__ = ["EventLog", "Simulation", "canonical_detail", "format_event"]


def _fmt_value(v) -> str:
    """One deterministic rendering per value type.  Floats go through
    ``%.9g`` (enough digits to distinguish any two virtual times the
    sim produces, few enough that the text is platform-stable); bools
    before ints because ``bool`` is an ``int`` subclass."""
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        return format(float(v), ".9g")
    return str(v)


def canonical_detail(**detail) -> str:
    """The sorted-key ``k=v`` tail of the canonical event rendering,
    with every value through :func:`_fmt_value` (``%.9g`` floats).
    Nested dicts canonicalize recursively as ``{k=v ...}`` and
    lists/tuples as ``[v ...]``, so a telemetry snapshot digests
    byte-stably too.  Shared by :func:`format_event` and the decision
    flight recorder (:mod:`bluefog_tpu.observe.blackbox`), which must
    agree on what "byte-stable" means."""

    def render(v) -> str:
        if isinstance(v, dict):
            inner = " ".join(
                f"{k}={render(v[k])}" for k in sorted(v, key=str))
            return "{" + inner + "}"
        if isinstance(v, (list, tuple)):
            return "[" + " ".join(render(x) for x in v) + "]"
        return _fmt_value(v)

    return " ".join(f"{k}={render(detail[k])}" for k in sorted(detail))


def format_event(t: float, kind: str, actor: str = "", **detail) -> str:
    """The canonical one-line event rendering:
    ``<t sec> <kind> <actor> k=v ...`` with detail keys sorted — the
    byte-stable unit the log digest folds."""
    parts = [format(float(t), ".9f"), str(kind)]
    if actor:
        parts.append(str(actor))
    if detail:
        parts.append(canonical_detail(**detail))
    return " ".join(parts)


class EventLog:
    """Append-only event record with a streaming SHA-256 digest.

    ``keep_lines=True`` (the default) retains the formatted lines for
    inspection/assertions; the million-request bench passes ``False``
    and relies on the digest alone — the memory cost of the log is then
    one hash state regardless of trace length."""

    def __init__(self, keep_lines: bool = True):
        self._sha = hashlib.sha256()
        self.lines: Optional[List[str]] = [] if keep_lines else None
        self.n = 0

    def record(self, t: float, kind: str, actor: str = "",
               **detail) -> str:
        line = format_event(t, kind, actor, **detail)
        self._sha.update(line.encode("utf-8"))
        self._sha.update(b"\n")
        if self.lines is not None:
            self.lines.append(line)
        self.n += 1
        return line

    def digest(self) -> str:
        """Hex SHA-256 over every line recorded so far — the
        machine-checked determinism claim: two runs with the same seed
        must produce the same digest, byte for byte."""
        return self._sha.hexdigest()


class Simulation:
    """Seeded event heap over a shared :class:`VirtualClock`.

    Events are ``(t, seq, kind, actor, fn, detail)``; ``seq`` is the
    insertion counter, so simultaneous events fire in schedule order —
    no hash/dict iteration order anywhere near the pop sequence.  Every
    pop jumps the clock to the event time, records the event, then runs
    ``fn(sim, t)`` (which may schedule more).  ``rng`` is the one
    entropy source actors may draw from."""

    def __init__(self, *, seed: int = 0,
                 clock: Optional[VirtualClock] = None,
                 log: Optional[EventLog] = None):
        self.clock = clock if clock is not None else VirtualClock()
        self.log = log if log is not None else EventLog()
        self.rng = np.random.RandomState(seed)
        self._heap: List[Tuple] = []
        self._seq = 0

    @property
    def pending(self) -> int:
        return len(self._heap)

    def at(self, t: float, kind: str,
           fn: Optional[Callable] = None,
           actor: str = "", **detail) -> None:
        """Schedule ``kind`` (and optional callback ``fn(sim, t)``) at
        absolute virtual time ``t`` — which must not be in the past:
        the log is append-only in time."""
        t = float(t)
        if t < self.clock.t:
            raise ValueError(
                f"cannot schedule at t={t} behind the clock "
                f"(now={self.clock.t})")
        heapq.heappush(self._heap,
                       (t, self._seq, str(kind), str(actor), fn, detail))
        self._seq += 1

    def after(self, dt: float, kind: str,
              fn: Optional[Callable] = None,
              actor: str = "", **detail) -> None:
        """Schedule ``dt`` virtual seconds from now."""
        self.at(self.clock.t + float(dt), kind, fn, actor=actor,
                **detail)

    def run(self, until: Optional[float] = None,
            max_events: Optional[int] = None) -> int:
        """Pop and deliver events in time order.  ``until`` bounds the
        delivered window INCLUSIVELY (events at exactly ``until`` fire)
        and the clock lands on ``until`` even if the heap ran dry
        first; without it the heap drains completely.  Returns the
        number of events delivered."""
        delivered = 0
        while self._heap:
            if max_events is not None and delivered >= max_events:
                break
            t = self._heap[0][0]
            if until is not None and t > until:
                break
            t, _, kind, actor, fn, detail = heapq.heappop(self._heap)
            self.clock.jump_to(t)
            self.log.record(t, kind, actor, **detail)
            if fn is not None:
                fn(self, t)
            delivered += 1
        if until is not None:
            self.clock.jump_to(until)
        return delivered
