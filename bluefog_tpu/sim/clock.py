"""The one virtual clock every simulated component shares.

Three benchmarks grew private copies of the same two-line clock
(``fleet_serving.py`` / ``chaos_serving.py`` ``_Clock``) plus a wire
variant (``chaos_adaptive_topology.py``); this module is the single
implementation they now import, and the clock every
:mod:`bluefog_tpu.sim` actor is built around.

The contract is deliberately tiny so the clock is injectable anywhere a
``time.monotonic``-shaped callable is accepted (``ServingEngine``,
``FleetRouter``, ``ServingMetrics`` heartbeats): calling the clock reads
virtual seconds; nothing inside :mod:`bluefog_tpu.sim` ever reads the
wall clock (the ``wallclock-in-sim`` bfcheck lint rule enforces this
mechanically — see docs/simulation.md).  Determinism follows: the same
seed replays the same virtual timeline byte-for-byte on any host, at
any host speed.
"""

from __future__ import annotations

__all__ = ["VirtualClock"]


class VirtualClock:
    """Monotone virtual time in seconds.  ``clock()`` reads it; the
    simulation driver advances it (``advance``/``jump_to``) — never the
    actors, so one tick's readers all agree on "now"."""

    def __init__(self, start: float = 0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` virtual seconds (``dt >= 0`` —
        virtual time never rewinds; a negative step would reorder
        already-logged events)."""
        if dt < 0:
            raise ValueError(f"virtual time cannot rewind (dt={dt})")
        self.t += float(dt)
        return self.t

    def jump_to(self, t: float) -> float:
        """Advance to absolute time ``t`` if it is in the future; a
        past ``t`` is a no-op (idle-jump semantics: the fleet loop
        jumps to the next arrival only when everyone is idle)."""
        self.t = max(self.t, float(t))
        return self.t
