"""Generate the API reference (markdown) by introspection.

The reference ships a 16-file Sphinx tree with autodoc pages
(reference docs/torch_api.rst, tensorflow_api.rst, topo_api.rst,
bluefog_ops.rst, ...).  This environment has no sphinx/pdoc, so this is
a self-contained autodoc: it imports every public module, walks its
public surface (``__all__`` when declared, else public names defined in
the module), and emits one markdown page per module with signatures +
docstrings, plus an index.  Deterministic output — rerunning on an
unchanged tree is a no-op, so CI can assert freshness.

Run (CI-runnable):  PYTHONPATH=. python docs/gen_api_reference.py
Output:             docs/api/*.md
"""

import dataclasses
import importlib
import inspect
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

OUT_DIR = os.environ.get(
    "BLUEFOG_API_REF_OUT",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "api"))

# module -> one-line description for the index
MODULES = [
    ("bluefog_tpu", "top-level package: init/size/rank + the full op API"),
    ("bluefog_tpu.api", "the flat op API (collectives, windows, timeline)"),
    ("bluefog_tpu.topology", "graph generators, weights, dynamic iterators"),
    ("bluefog_tpu.topology.graphs",
     "static graph generators (exp2, ring, mesh, star) + weights"),
    ("bluefog_tpu.topology.dynamic",
     "dynamic one-peer schedules: world-level rounds + iterators"),
    ("bluefog_tpu.topology.spec",
     "device-ready Topology/DynamicTopology shift-class specs"),
    ("bluefog_tpu.topology.torus", "physical ICI torus routing/congestion"),
    ("bluefog_tpu.topology.compiler",
     "topology compiler: pod cost model + schedule synthesis"),
    ("bluefog_tpu.topology.control",
     "closed-loop control plane: detect, re-plan, hot-swap"),
    ("bluefog_tpu.optim", "distributed optimizer wrappers (eager API)"),
    ("bluefog_tpu.optim.functional",
     "jitted whole-pytree train steps (SPMD API)"),
    ("bluefog_tpu.resilience",
     "resilience: fault injection, detection, healing, guarded rollback"),
    ("bluefog_tpu.resilience.faults",
     "deterministic fault-injection plans (the chaos harness)"),
    ("bluefog_tpu.resilience.detector",
     "failure detection: numeric health + liveness heartbeats"),
    ("bluefog_tpu.resilience.healing",
     "topology healing: dead-rank weight re-planning"),
    ("bluefog_tpu.resilience.runner",
     "run_resilient: the skip/heal/rollback control loop"),
    ("bluefog_tpu.elastic",
     "elastic membership: ranks that join, not just die"),
    ("bluefog_tpu.elastic.membership",
     "membership lifecycle + grow_weights (heal's exact inverse)"),
    ("bluefog_tpu.elastic.bootstrap",
     "joiner bootstrap: annealed pull weights + disagreement gate"),
    ("bluefog_tpu.models", "model zoo: Llama, ResNet, ViT, MNIST nets"),
    ("bluefog_tpu.models.llama", "Llama config/stack, TP/EP/vocab-parallel"),
    ("bluefog_tpu.models.generate", "K/V-cached autoregressive decode"),
    ("bluefog_tpu.models.quant", "int8 weight quantization for decode"),
    ("bluefog_tpu.serving.engine",
     "continuous-batching serving engine (slot-pooled K/V decode)"),
    ("bluefog_tpu.serving.kv_pool", "fixed-capacity K/V cache slot pool"),
    ("bluefog_tpu.serving.prefix_cache",
     "chunk-hashed prefix/KV reuse (host-side LRU of prompt-chunk K/V)"),
    ("bluefog_tpu.serving.fleet",
     "gossip-fed multi-replica request router (no central balancer)"),
    ("bluefog_tpu.serving.scheduler",
     "FIFO admission, deadlines, backpressure"),
    ("bluefog_tpu.serving.metrics",
     "serving metrics (TTFT, tokens/s) + request timeline spans"),
    ("bluefog_tpu.serving.resilience",
     "serving chaos: replica faults, token-exact failover, seeded "
     "backoff"),
    ("bluefog_tpu.observe",
     "unified observability: metrics, spans, step profiles, exporters"),
    ("bluefog_tpu.observe.registry",
     "metrics registry: counters, gauges, windowed histograms"),
    ("bluefog_tpu.observe.tracer",
     "span tracer: nested spans, instants, per-thread tracks"),
    ("bluefog_tpu.observe.stepprof",
     "HLO-attributed step profiler (profile_step / StepProfile)"),
    ("bluefog_tpu.observe.export",
     "exporters: Prometheus text, JSONL events, Chrome trace, snapshot"),
    ("bluefog_tpu.observe.fleet",
     "fleet telemetry: push-sum metric gossip, edge traffic, stragglers"),
    ("bluefog_tpu.observe.blackbox",
     "decision flight recorder: causal audit ring, replay, explain CLI"),
    ("bluefog_tpu.parallel.collectives",
     "XLA collective data plane (mesh ops)"),
    ("bluefog_tpu.parallel.ring_attention", "ring/blockwise attention (SP)"),
    ("bluefog_tpu.parallel.ulysses", "all-to-all sequence parallelism"),
    ("bluefog_tpu.parallel.pipeline", "GPipe + circular pipeline schedules"),
    ("bluefog_tpu.parallel.pallas_attention", "Pallas flash attention"),
    ("bluefog_tpu.parallel.pallas_decode",
     "Pallas fused decode-attention step"),
    ("bluefog_tpu.windows", "one-sided window ops (win_put/get/update)"),
    ("bluefog_tpu.compressor", "gradient compression (TopK/RandomK/int8)"),
    ("bluefog_tpu.checkpoint", "orbax checkpoint/resume wrappers"),
    ("bluefog_tpu.data", "DataLoader + DistributedSampler (C++ prefetch)"),
    ("bluefog_tpu.timeline", "Chrome-trace timeline"),
    ("bluefog_tpu.interop.torch_adapter", "torch tensor interop"),
    ("bluefog_tpu.interop.tf_adapter", "TensorFlow bridge (eager + graph)"),
    ("bluefog_tpu.interop.hf_llama", "HuggingFace Llama checkpoint import"),
    ("bluefog_tpu.run.run", "bfrun launcher (local + multi-host)"),
    ("bluefog_tpu.utility", "broadcast/allreduce convenience helpers"),
    ("bluefog_tpu.config", "environment-variable configuration"),
    ("bluefog_tpu.sim",
     "discrete-event fleet simulator: real control plane, virtual time"),
    ("bluefog_tpu.sim.clock",
     "virtual clock: monotonic simulated seconds, no wall reads"),
    ("bluefog_tpu.sim.engine",
     "event heap + streaming event log (byte-stable digests)"),
    ("bluefog_tpu.sim.cost",
     "calibrated cost model: virtual seconds per unit of real work"),
    ("bluefog_tpu.sim.wire",
     "per-step virtual transport billing the telemetry registry"),
    ("bluefog_tpu.sim.traces",
     "request traces + membership churn schedules (seeded)"),
    ("bluefog_tpu.sim.serving",
     "simulated replicas + lockstep fleet around the real router"),
    ("bluefog_tpu.sim.training",
     "simulated training fleet driving the real control plane"),
    ("bluefog_tpu.moe",
     "MoE expert parallelism: compiled a2a dispatch + expert sharding"),
    ("bluefog_tpu.moe.dispatch",
     "all-to-all dispatch plans, route tables, capacity healing"),
    ("bluefog_tpu.moe.layer",
     "top-k routed MoE layer + the expert-sharded loss"),
    ("bluefog_tpu.analysis",
     "static contract checker (bfcheck): findings + baseline"),
    ("bluefog_tpu.analysis.lint",
     "AST lint: env reads, host syncs, traced-if, weight bypass"),
    ("bluefog_tpu.analysis.jaxpr_check",
     "jaxpr/HLO sweep: weights-as-data, divergent cond, collectives"),
]


def _public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    names = []
    for name, obj in vars(mod).items():
        if name.startswith("_"):
            continue
        owner = getattr(obj, "__module__", None)
        if inspect.ismodule(obj):
            continue
        if owner is not None and owner != mod.__name__:
            continue
        names.append(name)
    return names


def _strip_addresses(s: str) -> str:
    """Drop runtime memory addresses (e.g. flax's module sentinel
    defaults) so regeneration on an unchanged tree is byte-identical."""
    return re.sub(r" at 0x[0-9a-f]+", "", s)


def _signature(obj) -> str:
    try:
        return _strip_addresses(str(inspect.signature(obj)))
    except (ValueError, TypeError):
        return "(...)"


def _doc(obj) -> str:
    doc = inspect.getdoc(obj)
    return _strip_addresses(doc.strip()) if doc else ""


def _render_function(name, fn, depth="###"):
    out = [f"{depth} `{name}{_signature(fn)}`", ""]
    doc = _doc(fn)
    if doc:
        out += [doc, ""]
    return out


def _render_class(name, cls):
    out = [f"### class `{name}`", ""]
    doc = _doc(cls)
    if doc:
        out += [doc, ""]
    if dataclasses.is_dataclass(cls):
        out += ["**Fields:**", ""]
        for f in dataclasses.fields(cls):
            if f.name in ("parent", "name"):  # flax Module plumbing
                continue
            default = ""
            if f.default is not dataclasses.MISSING:
                # strip runtime memory addresses (sentinel objects) so
                # regeneration on an unchanged tree is byte-identical
                rep = re.sub(r" at 0x[0-9a-f]+", "", repr(f.default))
                default = f" = `{rep}`"
            elif f.default_factory is not dataclasses.MISSING:
                default = " (factory)"
            out.append(f"- `{f.name}`{default}")
        out.append("")
    for mname, meth in sorted(vars(cls).items()):
        if mname.startswith("_") or not callable(meth):
            continue
        fn = meth.__func__ if isinstance(meth, (classmethod,
                                                staticmethod)) else meth
        if not (inspect.isfunction(fn) or inspect.ismethod(fn)):
            continue
        out += _render_function(f"{name}.{mname}", fn, depth="####")
    return out


def render_module(modname: str) -> str:
    mod = importlib.import_module(modname)
    lines = [f"# `{modname}`", ""]
    doc = _doc(mod)
    if doc:
        lines += [doc, ""]
    names = _public_names(mod)
    consts, funcs, classes = [], [], []
    for name in names:
        obj = getattr(mod, name, None)
        if obj is None:
            continue
        if inspect.isclass(obj):
            classes.append((name, obj))
        elif callable(obj):
            funcs.append((name, obj))
        else:
            consts.append((name, obj))
    if funcs:
        lines += ["## Functions", ""]
        for name, fn in funcs:
            lines += _render_function(name, fn)
    if classes:
        lines += ["## Classes", ""]
        for name, cls in classes:
            lines += _render_class(name, cls)
    if consts:
        lines += ["## Constants", ""]
        for name, val in consts:
            rep = re.sub(r" at 0x[0-9a-f]+", "", repr(val))
            if len(rep) > 120:
                rep = rep[:117] + "..."
            lines += [f"- `{name}` = `{rep}`"]
        lines.append("")
    return "\n".join(lines).rstrip() + "\n"


def main() -> int:
    os.makedirs(OUT_DIR, exist_ok=True)
    index = ["# bluefog_tpu API reference", "",
             "Generated by `python docs/gen_api_reference.py` "
             "(introspection autodoc — no sphinx in this environment).",
             ""]
    for modname, desc in MODULES:
        page = modname.replace(".", "_") + ".md"
        content = render_module(modname)
        with open(os.path.join(OUT_DIR, page), "w") as f:
            f.write(content)
        index.append(f"- [`{modname}`]({page}) — {desc}")
        print(f"wrote docs/api/{page}")
    index.append("")
    with open(os.path.join(OUT_DIR, "index.md"), "w") as f:
        f.write("\n".join(index))
    print(f"wrote docs/api/index.md ({len(MODULES)} modules)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
